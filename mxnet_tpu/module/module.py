"""``mx.mod.Module`` — the legacy symbolic trainer.

Reference: python/mxnet/module/ (base_module.py fit loop, module.py bind/
init_params/init_optimizer/forward_backward/update, SURVEY.md §3.4).
DataParallelExecutorGroup's multi-GPU batch slicing is absorbed by sharded
arrays (SURVEY.md §2.5 DP row), so one Executor serves all devices.
"""
from __future__ import annotations

import logging
import time

import numpy as _np

from ..base import MXNetError
from ..context import current_context, cpu
from ..ndarray.ndarray import NDArray, zeros as nd_zeros
from ..ndarray import utils as nd_utils
from .. import initializer as init_mod
from .. import optimizer as opt_mod
from .. import metric as metric_mod
from .executor import Executor

__all__ = ["BaseModule", "Module", "BatchEndParam", "save_checkpoint_arrays",
           "load_checkpoint"]


class BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def save_checkpoint_arrays(prefix, epoch, symbol, arg_params, aux_params):
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd_utils.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_checkpoint(prefix, epoch):
    """Reference: mx.model.load_checkpoint."""
    from .. import symbol as sym_mod
    symbol = None
    import os
    if os.path.exists(f"{prefix}-symbol.json"):
        try:
            symbol = sym_mod.load(f"{prefix}-symbol.json")
        except MXNetError:
            symbol = None
    loaded = nd_utils.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return symbol, arg_params, aux_params


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False

    # -- high-level API (reference base_module.py) ----------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        assert num_epoch is not None, "please specify number of epochs"
        initializer = initializer or init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        validation_metric = validation_metric or eval_metric
        if monitor is not None:
            self.install_monitor(monitor)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(batch)
                self.update()
                if monitor is not None:
                    monitor.toc_print()
                self.update_metric(eval_metric, batch.label)
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch, nbatch, eval_metric)
                    for cb in _as_list(batch_end_callback):
                        cb(params)
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            if epoch_end_callback is not None:
                arg_params, aux_params = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

    def score(self, eval_data, eval_metric, num_batch=None, reset=True,
              epoch=0):
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, reset=True):
        from .. import ndarray as nd
        if reset:
            eval_data.reset()
        outputs = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outputs.append(self.get_outputs()[0])
        return nd.concatenate(outputs, axis=0)

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def install_monitor(self, mon):
        """Attach a mx.monitor.Monitor (reference Module.install_monitor)."""
        mon.install(self)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger)
        self.symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._contexts = (list(context) if isinstance(context, (list, tuple))
                          else ([context] if context is not None else []))
        self._context = self._contexts[0] if self._contexts else None
        self._fixed_param_names = set(fixed_param_names or [])
        self._exec = None
        self._optimizer = None
        self._updater_states = {}
        self._kvstore = None
        self._update_on_kvstore = False
        self._batch_size = None
        self._mesh = None   # multi-device DP: set by bind when len(ctx) > 1
        self._preloaded_params = None   # set by Module.load
        self._preload_opt_states = None  # set by Module.load(...states)
        self._group2ctxs = group2ctxs

    # -- bind -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        shapes = {}
        for desc in data_shapes:
            name, shape = (desc.name, desc.shape) if hasattr(desc, "name") \
                else desc
            shapes[name] = shape
        for desc in (label_shapes or []):
            name, shape = (desc.name, desc.shape) if hasattr(desc, "name") \
                else desc
            shapes[name] = shape
        arg_names = self.symbol.list_arguments()
        reqs = {}
        for n in arg_names:
            if n in shapes and (n in self._data_names or
                                n in self._label_names):
                reqs[n] = "null"
            elif n in self._fixed_param_names:
                reqs[n] = "null"
            else:
                reqs[n] = grad_req
        shared_args = None
        if shared_module is not None:
            if shared_module._exec is None:
                raise MXNetError(
                    "shared_module must be bound (and initialized) before "
                    "it can share parameters — reference Module.bind "
                    "asserts the same precondition")
            # reference shared_module bind: this executor ADOPTS the other
            # module's parameter arrays (one storage, mutation-on-handle)
            # instead of allocating its own; the shared module's symbol
            # must own every parameter of this one
            io_names = set(self._data_names) | set(self._label_names)
            src = shared_module._exec.arg_dict
            shared_args = {n: src[n] for n in arg_names
                           if n in src and n not in io_names}
            missing = [n for n in arg_names
                       if n not in io_names and n not in src]
            if missing:
                raise MXNetError(
                    f"shared_module does not own parameters {missing}; "
                    "the sharing module's symbol must be a parameter "
                    "superset (reference Module.bind(shared_module=...) "
                    "requires the same)")
        self._exec = Executor(self.symbol, self._context, shapes,
                              args=shared_args, grad_req=reqs,
                              group2ctxs=self._group2ctxs)
        # parameter shapes follow from the data shapes via the executor's
        # InferShape remnant (SURVEY.md §2.1 Symbol/nnvm row)
        self._exec._materialize_params()
        first = data_shapes[0]
        self._batch_size = (first.shape if hasattr(first, "shape")
                            else first[1])[0]
        if len(self._contexts) > 1:
            self._bind_mesh()
        self.binded = True
        self.for_training = for_training

    def _bind_mesh(self):
        """Multi-context bind = the DataParallelExecutorGroup role
        (reference python/mxnet/module/executor_group.py, SURVEY.md §3.4):
        instead of one executor per context with explicit batch slicing,
        the contexts form a 'dp' mesh — parameters are replicated over it,
        the batch is sharded over it in forward(), and every eager op then
        executes SPMD with the gradient psum implied by the sharding
        algebra."""
        import jax
        import numpy as _np2
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = []
        for c in self._contexts:
            d = getattr(c, "jax_device", None)
            if d is None:
                idx = getattr(c, "device_id", 0) or 0
                d = jax.devices()[idx % len(jax.devices())]
            devs.append(d)
        if self._batch_size and self._batch_size % len(devs):
            raise MXNetError(
                f"batch size {self._batch_size} must be divisible by the "
                f"number of contexts {len(devs)}")
        from ..parallel.mesh import AXIS_DP
        self._mesh = Mesh(_np2.array(devs), (AXIS_DP,))

    def _replicate_params(self):
        """Pin parameters replicated on the dp mesh. Runs AFTER they hold
        their real values (init_params/set_params overwrite data, so
        replicating at bind time would be undone immediately)."""
        if self._mesh is None:
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(self._mesh, P())
        for name, arr in self._exec.arg_dict.items():
            if name not in self._data_names and \
                    name not in self._label_names:
                arr._set_data(jax.device_put(arr.data, rep))

    def _shard_batch(self, arr):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        data = arr.data if hasattr(arr, "data") else arr
        from ..parallel.mesh import AXIS_DP
        return jax.device_put(data,
                              NamedSharding(self._mesh, P(AXIS_DP)))

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if arg_params is None and aux_params is None and not force_init \
                and self._preloaded_params is not None:
            # Module.load stashed the checkpoint here; without this the
            # loaded weights would be silently re-initialized (r2 missing
            # #4b). force_init=True deliberately re-randomizes instead.
            # Reference: Module.load -> fit(arg_params=...) flow.
            arg_params, aux_params = self._preloaded_params
        initializer = initializer or init_mod.Uniform(0.01)
        for name, arr in self._exec.arg_dict.items():
            if name in self._data_names or name in self._label_names:
                continue
            if arg_params and name in arg_params:
                arr._set_data(arg_params[name].data)
            else:
                initializer(init_mod.InitDesc(name), arr)
        self._replicate_params()
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        from .. import kvstore as kvs
        if kvstore:
            self._kvstore = kvs.create(kvstore) if isinstance(kvstore, str) \
                else kvstore
        if isinstance(optimizer, str):
            params = dict(optimizer_params)
            # reference Module.init_optimizer defaults rescale_grad to
            # 1/batch_size — and for dist SYNC stores 1/(batch_size *
            # num_workers) since those SUM worker grads; dist_async
            # applies each worker's grad individually, so no extra factor
            # (python/mxnet/module/module.py: batch_size *= num_workers
            # only when 'dist' in type and '_sync' in type)
            if "rescale_grad" not in params and self._batch_size:
                n = 1
                if self._kvstore is not None and \
                        (("dist" in self._kvstore.type and
                          "_sync" in self._kvstore.type) or
                         # the adapter facades SUM like a dist sync store
                         self._kvstore.type in ("horovod", "byteps")):
                    n = self._kvstore.num_workers
                params["rescale_grad"] = 1.0 / (self._batch_size * n)
            optimizer = opt_mod.create(optimizer, **params)
        self._optimizer = optimizer
        if self._kvstore is not None:
            import os
            # reference default: optimizer runs ON the store (server-side
            # update, kvstore_dist_server.h DataHandleEx); opt out via env
            # like MXNET_UPDATE_ON_KVSTORE=0
            self._update_on_kvstore = os.environ.get(
                "MXTPU_UPDATE_ON_KVSTORE",
                os.environ.get("MXNET_UPDATE_ON_KVSTORE", "1")) == "1"
            if self._kvstore.type in ("horovod", "byteps"):
                # reference model/module force update_on_kvstore=False for
                # the adapters (no server to run the optimizer on)
                self._update_on_kvstore = False
            if self._kvstore.type == "dist_async" and \
                    not self._update_on_kvstore:
                # the PS table holds WEIGHTS; a pushpull would hand the
                # local optimizer a weight as if it were a gradient.
                # Reference refuses the combination too (mxnet.model
                # _update_params asserts update_on_kvstore for async).
                raise MXNetError(
                    "dist_async requires update_on_kvstore=1 (the "
                    "server applies the optimizer)")
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
            # register every trainable param in ONE list call; dist stores
            # broadcast rank 0's values (bucketed — one collective per
            # 25MB, not per param) so all workers start identical
            # (SURVEY.md §3.5 "worker 0: kv.init -> broadcast")
            names = self._trainable_names()   # name keys, see update()
            arrs = [self._exec.arg_dict[n] for n in names]
            self._kvstore.init(names, arrs)
            if self._kvstore.num_workers > 1:
                self._kvstore.pull(names, out=arrs)
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def _trainable_names(self):
        return [n for n in self.symbol.list_arguments()
                if n not in self._data_names and n not in self._label_names
                and n not in self._fixed_param_names]

    # -- compute --------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        feed = {}
        for name, arr in zip(self._data_names, data_batch.data):
            feed[name] = self._shard_batch(arr) if self._mesh is not None \
                else arr
        if data_batch.label is not None:
            for name, arr in zip(self._label_names, data_batch.label):
                feed[name] = self._shard_batch(arr) \
                    if self._mesh is not None else arr
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads)

    def update(self):
        """Optimizer step. With a kvstore this routes gradients through it
        (reference module.py update -> kvstore.push/pull, SURVEY.md §3.4):
        update_on_kvstore pushes the grad and pulls the store-updated
        weight; otherwise push+pull allreduces the grad and the local
        optimizer applies it — either way N dist workers stay bitwise in
        step (r2 missing #4a)."""
        assert self.optimizer_initialized
        # keys are parameter NAMES (not positions): updater state and
        # kvstore slots then stay correct when modules with different
        # parameter subsets share an optimizer (BucketingModule buckets)
        keys, arrs, grads = [], [], []
        for name in self._trainable_names():
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            keys.append(name)
            arrs.append(self._exec.arg_dict[name])
            grads.append(grad)
        if not keys:
            return
        # ONE list push/pull so the dist store coalesces all params into
        # BIGARRAY_BOUND buckets (kvstore._bucketed_allreduce) instead of
        # one collective round per parameter
        if self._kvstore is not None and self._update_on_kvstore:
            self._kvstore.push(keys, grads)
            self._kvstore.pull(keys, out=arrs)
            return
        if self._kvstore is not None:
            self._kvstore.pushpull(keys, grads, out=grads)
        for name, arr, grad in zip(keys, arrs, grads):
            if name not in self._updater_states:
                self._updater_states[name] = \
                    self._optimizer.create_state(name, arr)
            self._optimizer.update(name, arr, grad,
                                   self._updater_states[name])

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def get_params(self):
        arg_params = {}
        for name, arr in self._exec.arg_dict.items():
            if name not in self._data_names and name not in self._label_names:
                arg_params[name] = arr.copy()
        return arg_params, {}

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg_params, aux_params = self.get_params()
        save_checkpoint_arrays(prefix, epoch, self.symbol, arg_params,
                               aux_params)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    def save_optimizer_states(self, fname):
        """Reference Module.save_optimizer_states: momentum/Adam state per
        trainable param. With update_on_kvstore the state lives in the
        STORE's updater — delegate there (reference does the same);
        otherwise serialize the local states through the shared NDArray
        container (state:<idx>:<component> keys) — no pickle."""
        assert self.optimizer_initialized, "init_optimizer first"
        if self._update_on_kvstore and self._kvstore is not None:
            return self._kvstore.save_optimizer_states(fname)
        flat = {}
        for name, st in self._updater_states.items():
            comps = st if isinstance(st, (list, tuple)) else [st]
            for j, c in enumerate(comps):
                if c is not None:
                    flat[f"state:{j}:{name}"] = c
        nd_utils.save(fname, flat)

    def load_optimizer_states(self, fname):
        """Reference Module.load_optimizer_states (after init_optimizer).
        Accepts both the current name-keyed format (state:<j>:<name>) and
        the earlier positional one (state:<idx>:<j>); kvstore-side states
        saved with positional keys are remapped to names on load."""
        assert self.optimizer_initialized, "init_optimizer first"
        names = self._trainable_names()
        if self._update_on_kvstore and self._kvstore is not None:
            self._kvstore.load_optimizer_states(fname)
            updater = self._kvstore._updater
            remapped = {}
            for k, v in updater.states.items():
                if isinstance(k, int) and 0 <= k < len(names):
                    remapped[names[k]] = v      # legacy positional key
                else:
                    remapped[k] = v
            updater.states = remapped
            return
        loaded = nd_utils.load(fname)
        for key, arr in loaded.items():
            _, a, b = key.split(":", 2)
            if b.isdigit():
                # legacy state:<idx>:<j>
                name, j = names[int(a)], int(b)
            else:
                j, name = int(a), b
            if name not in self._updater_states:
                self._updater_states[name] = self._optimizer.create_state(
                    name, self._exec.arg_dict[name])
            st = self._updater_states[name]
            target = st[j] if isinstance(st, (list, tuple)) else st
            target._set_data(arr.data)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._preloaded_params = (arg_params, aux_params)
        if load_optimizer_states:
            # applied once the optimizer exists (reference defers the same
            # way: preload_opt_states -> init_optimizer)
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod

    @property
    def output_shapes(self):
        return [o.shape for o in self._exec.outputs]


