"""``mx.operator`` — user-defined Python operators (CustomOp).

Reference: python/mxnet/operator.py (`CustomOp`, `CustomOpProp`,
`@mx.operator.register`, invoked via ``mx.nd.Custom(..., op_type=...)``)
over src/operator/custom/custom.cc. Semantics preserved: the op body is a
host Python callback with explicit ``forward``/``backward`` and
``assign(dst, req, src)`` write/add discipline; shape/type inference comes
from the Prop.

TPU mapping: custom ops run EAGERLY and record one tape node whose
pullback calls the user's ``backward`` — exactly the reference behavior
(custom ops are engine-thread Python callbacks there, and they break
fusion there too). A custom op inside a hybridized block therefore forces
that block onto the imperative path, mirroring the reference's
CachedOp-with-Custom dispatch. For compiled-speed custom kernels the
TPU-native route is a Pallas kernel behind ``apply_nary`` (see
ops/flash_attention.py as the exemplar).
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from . import _tape
from .ndarray.ndarray import NDArray, zeros as nd_zeros

__all__ = ["CustomOp", "CustomOpProp", "register", "get_registry", "Custom"]

_REGISTRY = {}


class CustomOp:
    """Base for user op bodies (reference mx.operator.CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad_req discipline."""
        if req == "null":
            return
        src = src if isinstance(src, NDArray) else NDArray(
            _ensure_jax(src))
        if req == "add":
            dst._set_data(dst.data + src.data)
        else:                       # "write" / "inplace"
            dst._set_data(src.data)


class CustomOpProp:
    """Op metadata + factory (reference mx.operator.CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, shapes, dtypes):
        raise NotImplementedError


def register(reg_name):
    """``@mx.operator.register("my_op")`` on a CustomOpProp subclass."""
    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError(
                f"register({reg_name!r}) expects a CustomOpProp subclass")
        _REGISTRY[reg_name] = prop_cls
        return prop_cls
    return do_register


def get_registry():
    return dict(_REGISTRY)


def _ensure_jax(x):
    import jax.numpy as jnp
    return x.data if isinstance(x, NDArray) else jnp.asarray(x)


def Custom(*inputs, op_type=None, **kwargs):
    """Invoke a registered custom op (reference mx.nd.Custom).

    Extra kwargs go to the Prop constructor (string-typed in the
    reference; here passed through as-is).
    """
    if op_type is None:
        raise MXNetError("Custom(...) requires op_type=")
    prop_cls = _REGISTRY.get(op_type)
    if prop_cls is None:
        raise MXNetError(f"custom op {op_type!r} is not registered "
                         f"(known: {sorted(_REGISTRY)})")
    prop = prop_cls(**kwargs)
    in_names = prop.list_arguments()
    if len(inputs) != len(in_names):
        raise MXNetError(
            f"custom op {op_type!r} expects {len(in_names)} inputs "
            f"{in_names}, got {len(inputs)}")
    in_data = [x if isinstance(x, NDArray) else NDArray(_ensure_jax(x))
               for x in inputs]

    in_shapes, out_shapes, aux_shapes = prop.infer_shape(
        [list(x.shape) for x in in_data])
    in_types, out_types, _ = prop.infer_type(
        [x.dtype for x in in_data])
    ctx = in_data[0].context if in_data else None
    op = prop.create_operator(ctx, out_shapes, out_types)

    out_data = [nd_zeros(tuple(s), ctx=ctx, dtype=t)
                for s, t in zip(out_shapes, out_types)]
    aux = [nd_zeros(tuple(s), ctx=ctx) for s in aux_shapes]

    is_train = _tape.is_training()
    n_out = len(out_data)
    with _tape.trace_scope():
        # the op BODY is not recorded (reference: custom callbacks run on
        # the engine thread outside autograd); only the single Custom
        # node below is, with the user's backward as its pullback
        op.forward(is_train=is_train, req=["write"] * n_out,
                   in_data=in_data, out_data=out_data, aux=aux)

    record = _tape.is_recording() and any(
        _tape._on_tape(x) for x in in_data)
    if record:
        def vjp_fn(cotangents):
            cots = cotangents if isinstance(cotangents, tuple) \
                else (cotangents,)
            out_grad = [NDArray(_ensure_jax(c)) for c in cots]
            in_grad = [nd_zeros(x.shape, ctx=ctx, dtype=x.dtype)
                       for x in in_data]
            with _tape.trace_scope():
                op.backward(req=["write"] * len(in_grad),
                            out_grad=out_grad, in_data=in_data,
                            out_data=out_data, in_grad=in_grad, aux=aux)
            return tuple(g.data for g in in_grad)

        _tape._STATE.counter += 1
        node = _tape.Node(list(in_data), vjp_fn,
                          [o.data for o in out_data],
                          _tape._STATE.counter, name=f"Custom({op_type})")
        for i, o in enumerate(out_data):
            o._node = node
            o._out_index = i
    return out_data[0] if n_out == 1 else out_data
