"""ctypes bindings to the native C++ runtime library (src/).

The native library accelerates host-side work that is NOT on the XLA compute
path (SURVEY.md design stance: XLA is the device runtime; the host runtime
around it is C++): RecordIO scanning/indexing and batch assembly with a
prefetching thread pool — the role of src/io/ + dmlc-core in the reference.

Falls back cleanly when the library has not been built
(`python setup_native.py build` produces libmxtpu.so next to this file).
"""
from __future__ import annotations

import ctypes
import os

_LIB = None
_TRIED = False


def _find_lib():
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.path.join(here, "libmxtpu.so"),
        os.path.join(here, "..", "..", "src", "build", "libmxtpu.so"),
        os.path.join(here, "..", "..", "build", "libmxtpu.so"),
    ]
    for c in candidates:
        if os.path.exists(c):
            return c
    return None


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _find_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.mxtpu_recordio_open.restype = ctypes.c_void_p
        lib.mxtpu_recordio_open.argtypes = [ctypes.c_char_p]
        lib.mxtpu_recordio_count.restype = ctypes.c_int64
        lib.mxtpu_recordio_count.argtypes = [ctypes.c_void_p]
        lib.mxtpu_recordio_read.restype = ctypes.c_int64
        lib.mxtpu_recordio_read.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p)]
        lib.mxtpu_recordio_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available():
    return _load() is not None


class NativeRecordFile:
    """Random-access view over a .rec file backed by the C++ reader
    (mmap + in-memory index, no per-read Python parsing)."""

    def __init__(self, path):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._handle = lib.mxtpu_recordio_open(path.encode())
        if not self._handle:
            raise IOError(f"cannot open {path}")
        self._count = lib.mxtpu_recordio_count(self._handle)

    def __len__(self):
        return self._count

    def __getitem__(self, i):
        ptr = ctypes.c_void_p()
        size = self._lib.mxtpu_recordio_read(self._handle, i,
                                             ctypes.byref(ptr))
        if size < 0:
            raise IndexError(i)
        return ctypes.string_at(ptr, size)

    def close(self):
        if self._handle:
            self._lib.mxtpu_recordio_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
