"""ctypes bindings to the native C++ runtime library (src/).

The native library accelerates host-side work that is NOT on the XLA compute
path (SURVEY.md design stance: XLA is the device runtime; the host runtime
around it is C++): RecordIO scanning/indexing and batch assembly with a
prefetching thread pool — the role of src/io/ + dmlc-core in the reference.

Falls back cleanly when the library has not been built
(`python setup_native.py build` produces libmxtpu.so next to this file).
"""
from __future__ import annotations

import ctypes
import os

_LIB = None
_TRIED = False


def _find_lib():
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.path.join(here, "libmxtpu.so"),
        os.path.join(here, "..", "..", "src", "build", "libmxtpu.so"),
        os.path.join(here, "..", "..", "build", "libmxtpu.so"),
    ]
    for c in candidates:
        if os.path.exists(c):
            return c
    return None


def _try_build():
    """Attempt a one-shot cmake build of src/ (first use on a fresh
    checkout). Logged, serialized via a file lock so concurrent processes
    (e.g. a distributed launch) don't race the build directory; failures
    leave the pure-Python path in charge."""
    import fcntl
    import logging
    import subprocess
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "..")
    src = os.path.join(root, "src")
    if not os.path.isfile(os.path.join(src, "CMakeLists.txt")):
        return
    build = os.path.join(src, "build")
    lock_path = os.path.join(src, ".build.lock")
    try:
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)  # another proc may be building
            if _find_lib() is not None:
                return
            logging.getLogger("mxnet_tpu").info(
                "building native library (src/ -> libmxtpu.so); "
                "set MXTPU_NO_NATIVE_BUILD=1 to skip")
            subprocess.run(["cmake", "-S", src, "-B", build],
                           capture_output=True, timeout=120, check=True)
            subprocess.run(["cmake", "--build", build],
                           capture_output=True, timeout=300, check=True)
    except Exception as exc:
        logging.getLogger("mxnet_tpu").info(
            "native library build failed (%s); using pure-Python IO", exc)


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = _find_lib()
    if path is None and os.environ.get("MXTPU_NO_NATIVE_BUILD") != "1":
        _try_build()
        path = _find_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.mxtpu_recordio_open.restype = ctypes.c_void_p
        lib.mxtpu_recordio_open.argtypes = [ctypes.c_char_p]
        lib.mxtpu_recordio_count.restype = ctypes.c_int64
        lib.mxtpu_recordio_count.argtypes = [ctypes.c_void_p]
        lib.mxtpu_recordio_read.restype = ctypes.c_int64
        lib.mxtpu_recordio_read.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p)]
        lib.mxtpu_recordio_close.argtypes = [ctypes.c_void_p]
        lib.mxtpu_recordio_writer_open.restype = ctypes.c_void_p
        lib.mxtpu_recordio_writer_open.argtypes = [ctypes.c_char_p]
        lib.mxtpu_recordio_writer_write.restype = ctypes.c_int64
        lib.mxtpu_recordio_writer_write.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        lib.mxtpu_recordio_writer_close.restype = ctypes.c_int
        lib.mxtpu_recordio_writer_close.argtypes = [ctypes.c_void_p]
        lib.mxtpu_jpeg_decode.restype = ctypes.c_int
        lib.mxtpu_jpeg_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]
        lib.mxtpu_prefetch_create.restype = ctypes.c_void_p
        lib.mxtpu_prefetch_create.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
        lib.mxtpu_prefetch_next.restype = ctypes.c_int64
        lib.mxtpu_prefetch_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_void_p)]
        lib.mxtpu_prefetch_reset.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        lib.mxtpu_prefetch_error.restype = ctypes.c_char_p
        lib.mxtpu_prefetch_error.argtypes = [ctypes.c_void_p]
        lib.mxtpu_prefetch_free.argtypes = [ctypes.c_void_p]
        lib.mxtpu_last_error.restype = ctypes.c_char_p
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available():
    return _load() is not None


class NativeRecordFile:
    """Random-access view over a .rec file backed by the C++ reader
    (mmap + in-memory index, no per-read Python parsing)."""

    def __init__(self, path):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._handle = lib.mxtpu_recordio_open(path.encode())
        if not self._handle:
            raise IOError(f"cannot open {path}")
        self._count = lib.mxtpu_recordio_count(self._handle)

    def __len__(self):
        return self._count

    def __getitem__(self, i):
        ptr = ctypes.c_void_p()
        size = self._lib.mxtpu_recordio_read(self._handle, i,
                                             ctypes.byref(ptr))
        if size < 0:
            raise IndexError(i)
        return ctypes.string_at(ptr, size)

    def close(self):
        if self._handle:
            self._lib.mxtpu_recordio_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordWriter:
    """Sequential RecordIO writer backed by the C++ library."""

    def __init__(self, path):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._handle = lib.mxtpu_recordio_writer_open(path.encode())
        if not self._handle:
            raise IOError(f"cannot open {path} for writing")

    def write(self, buf):
        pos = self._lib.mxtpu_recordio_writer_write(
            self._handle, buf, len(buf))
        if pos < 0:
            raise IOError("native record write failed: %s"
                          % self._lib.mxtpu_last_error().decode())
        return pos

    def close(self):
        if self._handle:
            rc = self._lib.mxtpu_recordio_writer_close(self._handle)
            self._handle = None
            if rc != 0:
                raise IOError("record file close failed "
                              "(data may be truncated)")

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def jpeg_decode(buf):
    """Decode a JPEG byte string to an HxWx3 uint8 numpy array (RGB)."""
    import numpy as np
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not built")
    h = ctypes.c_int32()
    w = ctypes.c_int32()
    c = ctypes.c_int32()
    if lib.mxtpu_jpeg_decode(buf, len(buf), None, 0,
                             ctypes.byref(h), ctypes.byref(w),
                             ctypes.byref(c)) != 0:
        raise ValueError("not a decodable JPEG")
    out = np.empty((h.value, w.value, 3), dtype=np.uint8)
    rc = lib.mxtpu_jpeg_decode(
        buf, len(buf), out.ctypes.data_as(ctypes.c_void_p),
        out.nbytes, ctypes.byref(h), ctypes.byref(w), ctypes.byref(c))
    if rc != 0:
        raise ValueError("JPEG decode failed")
    return out


class NativePrefetcher:
    """Prefetching batch loader over a .rec file (C++ worker threads).

    mode='bytes' yields lists of raw record payloads per batch.
    mode='image' yields (uint8 NHWC batch, float32 labels) per batch —
    records must be IRHeader+JPEG as written by pack_img/im2rec.
    """

    def __init__(self, rec_path, indices, batch_size, n_threads=4,
                 queue_depth=4, mode="bytes", edge=224, label_width=1):
        import numpy as np
        lib = _load()
        if lib is None:
            raise RuntimeError("native library not built")
        self._lib = lib
        self._np = np
        idx = np.asarray(indices, dtype=np.int64)
        self._n = len(idx)
        self.batch_size = batch_size
        self.mode = mode
        self.edge = edge
        self.label_width = label_width
        mode_i = 0 if mode == "bytes" else 1
        self._handle = lib.mxtpu_prefetch_create(
            rec_path.encode(), idx.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64)),
            len(idx), batch_size, n_threads, queue_depth, mode_i, edge,
            label_width)
        if not self._handle:
            raise IOError(f"cannot create prefetcher for {rec_path}")

    def __iter__(self):
        return self

    def __next__(self):
        np = self._np
        data = ctypes.c_void_p()
        size = ctypes.c_int64()
        aux = ctypes.c_void_p()
        n = self._lib.mxtpu_prefetch_next(
            self._handle, ctypes.byref(data), ctypes.byref(size),
            ctypes.byref(aux))
        if n == 0:
            raise StopIteration
        if n < 0:
            raise IOError("native prefetch failed: %s"
                          % self._lib.mxtpu_prefetch_error(
                              self._handle).decode())
        if self.mode == "bytes":
            raw = ctypes.string_at(data, size.value)
            offsets = np.ctypeslib.as_array(
                ctypes.cast(aux, ctypes.POINTER(ctypes.c_int64)),
                shape=(n + 1,))
            return [raw[offsets[i]:offsets[i + 1]] for i in range(n)]
        e = self.edge
        batch = np.ctypeslib.as_array(
            ctypes.cast(data, ctypes.POINTER(ctypes.c_uint8)),
            shape=(n, e, e, 3)).copy()
        labels = np.ctypeslib.as_array(
            ctypes.cast(aux, ctypes.POINTER(ctypes.c_float)),
            shape=(n, self.label_width)).copy()
        return batch, labels

    def reset(self, indices=None):
        """Restart the epoch without re-opening/re-scanning the .rec file;
        pass a new index schedule (e.g. reshuffled) or None to replay."""
        np = self._np
        if indices is None:
            self._lib.mxtpu_prefetch_reset(
                self._handle, None, 0)
        else:
            idx = np.asarray(indices, dtype=np.int64)
            self._lib.mxtpu_prefetch_reset(
                self._handle,
                idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(idx))

    def close(self):
        if self._handle:
            self._lib.mxtpu_prefetch_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
