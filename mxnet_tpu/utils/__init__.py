"""Internal utilities (native bindings live here)."""
from . import native
