"""``mx.init`` — weight initializers.

Reference: python/mxnet/initializer.py (Xavier, MSRAPrelu, Normal, Uniform,
Orthogonal, One/Zero/Constant, Mixed, @register). Samplers draw from the
framework PRNG stream (mx.random over JAX keys).
"""
from __future__ import annotations

import math
import re

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError, registry_create
from .ndarray import random as _rnd
from .ndarray.ndarray import NDArray

__all__ = ["Initializer", "Uniform", "Normal", "Constant", "Zero", "One",
           "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear", "LSTMBias",
           "Mixed", "register", "create", "InitDesc"]

register, create, _REGISTRY = registry_create("initializer")


class InitDesc(str):
    """Parameter name + attrs hint (reference: initializer.InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer; callable on (name, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("first argument must be a name string/InitDesc")
        name = desc.lower()
        if name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_one(desc, arr)
        elif name.endswith("beta"):
            self._init_zero(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        else:
            self._init_weight(desc, arr)

    # default fills
    def _init_bias(self, name, arr):
        arr._set_data(jnp.zeros(arr.shape, arr.data.dtype))

    def _init_zero(self, name, arr):
        arr._set_data(jnp.zeros(arr.shape, arr.data.dtype))

    def _init_one(self, name, arr):
        arr._set_data(jnp.ones(arr.shape, arr.data.dtype))

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def init_array(self, arr, name="weight"):
        self(name, arr)
        return arr

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr._set_data(jax.random.uniform(_rnd.next_key(), arr.shape,
                                         arr.data.dtype, -self.scale,
                                         self.scale))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr._set_data(self.sigma * jax.random.normal(
            _rnd.next_key(), arr.shape, arr.data.dtype))


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr._set_data(jnp.full(arr.shape, self.value, arr.data.dtype))


@register
@register("zeros")
class Zero(Constant):
    def __init__(self):
        super().__init__(0.0)


@register
@register("ones")
class One(Constant):
    def __init__(self):
        super().__init__(1.0)


def _fan(shape):
    if len(shape) < 2:
        return (shape[0] if shape else 1, shape[0] if shape else 1)
    hw = int(_np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * hw
    fan_out = shape[0] * hw
    return fan_in, fan_out


@register
class Xavier(Initializer):
    """Reference: initializer.Xavier (rnd_type uniform/gaussian,
    factor_type avg/in/out, magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        fan_in, fan_out = _fan(arr.shape)
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"bad factor_type {self.factor_type}")
        scale = math.sqrt(self.magnitude / max(factor, 1e-12))
        if self.rnd_type == "uniform":
            data = jax.random.uniform(_rnd.next_key(), arr.shape,
                                      arr.data.dtype, -scale, scale)
        elif self.rnd_type == "gaussian":
            data = scale * jax.random.normal(_rnd.next_key(), arr.shape,
                                             arr.data.dtype)
        else:
            raise MXNetError(f"bad rnd_type {self.rnd_type}")
        arr._set_data(data)


@register
class MSRAPrelu(Xavier):
    """Kaiming init (reference initializer.MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr._set_data(jnp.asarray(self.scale * q.reshape(arr.shape),
                                  dtype=arr.data.dtype))


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        weight = _np.zeros(int(_np.prod(arr.shape)), dtype="float32")
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(jnp.asarray(weight.reshape(shape), arr.data.dtype))


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference initializer.LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = _np.zeros(arr.shape, dtype="float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr._set_data(jnp.asarray(b, arr.data.dtype))

    _init_bias = _init_weight


class Mixed:
    """Patterns -> initializers (reference initializer.Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("len(patterns) != len(initializers)")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(f"parameter {name} did not match any pattern")


@register
class Load(Initializer):
    """Initialize from a dict (or .params file) of pre-trained arrays,
    delegating to ``default_init`` for missing names (reference
    initializer.Load; used to warm-start from checkpoints)."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        if isinstance(param, str):
            from .ndarray.utils import load as _load
            param = _load(param)
        if not isinstance(param, dict):
            raise MXNetError(
                "Load initializer requires NAMED arrays (a dict or a "
                ".params file saved with names)")
        self.param = {(k[4:] if k.startswith(("arg:", "aux:")) else k): v
                      for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, desc, arr):
        name = desc if isinstance(desc, str) else str(desc)
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"Load initializer: parameter {name} has shape "
                    f"{tuple(arr.shape)} but the source is "
                    f"{tuple(src.shape)}")
            # accept NDArray or raw numpy; cast to the PARAM's dtype like
            # the reference's arr[:] = src assignment
            raw = getattr(src, "data", src)
            arr._set_data(jnp.asarray(raw, arr.data.dtype))
            if self.verbose:
                import logging
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise MXNetError(
                    f"Load initializer: no value for {name} and no "
                    "default_init given")
            self.default_init(desc, arr)


@register
class FusedRNN(Initializer):
    """Initialize the PACKED fused-RNN parameter vector (nd.RNN layout:
    all weights layer/direction-major, then all biases — reference
    initializer.FusedRNN over rnn_cell.FusedRNNCell). Weight chunks use
    the wrapped initializer; biases are zeros except the LSTM forget
    gate, set to ``forget_bias``."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        super().__init__()
        if isinstance(init, str):
            init = create(init)
        self._init = init
        self._nh = num_hidden
        self._nl = num_layers
        self._mode = mode
        self._bidir = bidirectional
        self._forget_bias = forget_bias
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4,
                       "gru": 3}[mode]

    def _init_weight(self, name, arr):
        import numpy as _onp
        g, nh, nl = self._gates, self._nh, self._nl
        dirs = 2 if self._bidir else 1
        total = int(arr.shape[0]) if len(arr.shape) == 1 else int(
            _onp.prod(arr.shape))
        # infer the input size from the packed length
        #   total = sum_l dirs*(g*nh*in_l + g*nh*nh) + nl*dirs*2*g*nh
        fixed = nl * dirs * (g * nh * nh) + nl * dirs * 2 * g * nh \
            + (nl - 1) * dirs * (g * nh * (nh * dirs))
        rem = total - fixed
        if rem <= 0 or rem % (dirs * g * nh):
            raise MXNetError(
                f"FusedRNN: packed length {total} inconsistent with "
                f"mode={self._mode} num_hidden={nh} num_layers={nl} "
                f"bidirectional={self._bidir}")
        in0 = rem // (dirs * g * nh)
        out = _onp.empty((total,), _onp.float32)
        offs = 0
        for layer in range(nl):
            in_sz = in0 if layer == 0 else nh * dirs
            for _ in range(dirs):
                for rows, cols in ((g * nh, in_sz), (g * nh, nh)):
                    from .ndarray.ndarray import NDArray
                    chunk = NDArray(jnp.zeros((rows, cols), jnp.float32))
                    self._init._init_weight(name, chunk)
                    out[offs:offs + rows * cols] = \
                        chunk.asnumpy().ravel()
                    offs += rows * cols
        for layer in range(nl):
            for _ in range(dirs):
                for _bias in range(2):
                    b = _onp.zeros((g * nh,), _onp.float32)
                    if self._mode == "lstm":
                        # gate order i,f,g,o: forget gate is chunk 1
                        b[nh:2 * nh] = self._forget_bias
                    out[offs:offs + g * nh] = b
                    offs += g * nh
        arr._set_data(jnp.asarray(out, arr.data.dtype))
