"""Overlapped input pipeline: decode -> H2D -> compute run concurrently.

The MLPerf TPU-pod lesson (PAPERS.md: Kumar et al. on MLPerf-0.6 TPU-v3
pods and "Exploring the limits of Concurrency in ML Training on Google
TPUs"): at pod scale the step time is set by whichever of {host decode,
H2D transfer, device compute} is slowest — *if* they are pipelined.  Run
serially they add up.  This module provides the two pipeline stages the
reference framework ran inside its C++ engine (iter_prefetcher.h +
threaded decode pool):

``AsyncDecodeIter``
    fans a per-sample decode function out over a thread pool and yields
    in-order batches — the host-side stage.  JPEG decode in cv2/PIL
    releases the GIL, so threads scale to the core count.

``DevicePrefetcher``
    double-buffers batches onto the device: a background thread
    ``jax.device_put``s batch N+1 (onto the active ``parallel`` mesh's
    data sharding when one is present) and *blocks on the transfer in
    the worker* while the consumer's step computes on batch N.  The
    consumer always receives device-resident arrays.

Both record per-stage wall time (decode / H2D / consumer compute /
consumer stall) in a ``PipelineStats`` so ``bench.py`` can report the
``input_pipeline`` block with an ``overlap_efficiency`` figure, and both
emit ``mx.profiler`` spans (``pipeline:decode`` / ``pipeline:h2d`` /
``pipeline:stall``) while a profile is running.
"""
from __future__ import annotations

import threading
import time
import queue as _queue
import weakref

import numpy as _np

from ..base import MXNetError
from ..lint import racecheck as _racecheck
from ..ndarray.ndarray import NDArray
from .. import telemetry as _telem
from ..telemetry import tracing as _tracing

__all__ = ["DevicePrefetcher", "AsyncDecodeIter", "PipelineStats",
           "default_prefetch_depth"]


def default_prefetch_depth():
    """Prefetch depth when the caller does not pass one:
    ``MXTPU_PREFETCH_DEPTH`` (>= 1), default 2 (double buffering)."""
    import os
    try:
        depth = int(os.environ.get("MXTPU_PREFETCH_DEPTH", "2"))
    except ValueError:
        raise MXNetError(
            f"MXTPU_PREFETCH_DEPTH={os.environ['MXTPU_PREFETCH_DEPTH']!r}"
            f": expected an integer >= 1")
    if depth < 1:
        raise MXNetError(
            f"MXTPU_PREFETCH_DEPTH must be >= 1, got {depth}")
    return depth


class PipelineStats:
    """Wall-time accumulator for the pipeline stages.

    ``decode`` / ``h2d`` are measured in the producer thread, ``compute``
    / ``stall`` in the consumer thread; because the stages overlap, the
    stage totals may legitimately sum to more than the elapsed wall
    time — that surplus *is* the overlap.
    """

    def __init__(self):
        self._lock = _racecheck.make_lock("PipelineStats._lock")
        self.decode_s = 0.0
        self.h2d_s = 0.0
        self.compute_s = 0.0
        self.stall_s = 0.0
        self.batches = 0
        self.h2d_bytes = 0

    def add(self, stage, dt, nbytes=0):
        with self._lock:
            setattr(self, stage + "_s", getattr(self, stage + "_s") + dt)
            if stage == "h2d":
                self.h2d_bytes += nbytes
                self.batches += 1
        # mirror onto the process telemetry registry (ISSUE 9): the
        # per-instance accumulator stays the bench `input_pipeline`
        # source; the registry is what a live scrape sees
        if _telem.enabled():
            _telem.observe(f"io.{stage}_ms", dt * 1e3)
            if stage == "h2d" and nbytes:
                _telem.inc("io.h2d_bytes", nbytes)

    def summary(self):
        """Per-stage ms/batch plus ``overlap_efficiency`` — the fraction
        of consumer wall time spent computing rather than stalled
        waiting for input (1.0 = input pipeline fully hidden)."""
        # snapshot under the lock (HB14: the producer thread's add() is
        # mid-update otherwise — a torn batches/decode_s pair skews the
        # per-batch figures); compute after release
        with self._lock:
            decode_s, h2d_s = self.decode_s, self.h2d_s
            compute_s, stall_s = self.compute_s, self.stall_s
            batches, h2d_bytes = self.batches, self.h2d_bytes
        n = max(batches, 1)
        busy = compute_s + stall_s
        out = {
            "batches": batches,
            "decode_ms_per_batch": round(decode_s / n * 1e3, 2),
            "h2d_ms_per_batch": round(h2d_s / n * 1e3, 2),
            "compute_ms_per_batch": round(compute_s / n * 1e3, 2),
            "stall_ms_per_batch": round(stall_s / n * 1e3, 2),
            "overlap_efficiency": round(compute_s / busy, 4)
            if busy > 0 else None,
        }
        if h2d_bytes and h2d_s > 0:
            out["h2d_gb_s"] = round(h2d_bytes / h2d_s / 1e9, 2)
        return out


def _profiler_span(name, t0, t1):
    from .. import profiler
    profiler.record_span(name, t0, t1)


# ---------------------------------------------------------------------------
# DevicePrefetcher
# ---------------------------------------------------------------------------

class _EndOfStream:
    pass


class _WorkerFailure:
    def __init__(self, exc):
        self.exc = exc


_END = _EndOfStream()


def _batch_nbytes(batch):
    """Exact bytes of one delivered batch (tuple/list of array leaves);
    0 when nothing measurable — the gauge then stays unset, never a
    fabricated zero (ISSUE 15 memory honesty)."""
    leaves = batch if isinstance(batch, (tuple, list)) else (batch,)
    total = 0
    for leaf in leaves:
        n = getattr(leaf, "nbytes", None)
        if n is None:
            n = getattr(getattr(leaf, "_data", None), "nbytes", None)
        if isinstance(n, int):
            total += n
    return total


class DevicePrefetcher:
    """Iterator wrapper that stages batches onto the device ahead of use.

    A background thread pulls batch N+1 from ``source``, ``device_put``s
    every array leaf (sharded over the mesh data axis when a mesh is
    given or a ``parallel.mesh_scope`` is active) and *blocks on the
    transfer in the worker thread*, so by the time the consumer asks for
    it the batch is already device-resident.  With ``depth=2`` this is
    classic double buffering: H2D of batch N+1 overlaps compute of N.

    ``source`` may yield ``io.DataBatch``es, (nested) tuples/lists of
    arrays, or single arrays; leaves may be numpy arrays, NDArrays, or
    jax arrays.  Structure is preserved; array leaves come back as
    device-resident :class:`NDArray`.

    Contract (tested under ``JAX_PLATFORMS=cpu``):

    * batches arrive in source order;
    * ``StopIteration`` propagates when the source is exhausted (and
      keeps raising on further calls);
    * an exception raised by the source or the transfer surfaces in the
      consumer at the position it occurred;
    * after exhaustion/close() the worker thread is joined — no leaked
      threads.
    """

    def __init__(self, source, depth=None, mesh=None, sharding=None,
                 batch_axis=0, data_axis=None, timeout=600.0,
                 to_device=True):
        if depth is None:
            depth = default_prefetch_depth()
        if depth < 1:
            raise MXNetError("DevicePrefetcher: depth must be >= 1")
        self._source = source
        self._depth = depth
        self._timeout = timeout
        self._batch_axis = batch_axis
        self._sharding = sharding
        self._to_device = to_device
        if mesh is None and sharding is None and to_device:
            from ..parallel.mesh import current_mesh
            mesh = current_mesh()
        self._mesh = mesh
        self._data_axis = data_axis
        self.stats = PipelineStats()
        self._queue = None
        self._thread = None
        self._stop = threading.Event()
        self._finished = False
        self._last_yield = None
        self._consumed = 0      # batches DELIVERED to the consumer: the
                                # honest resume cursor (the worker reads
                                # ahead of it by up to `depth` batches)
        self._skip = 0          # set_state replay-skip, applied by the
                                # worker on ITS source iterator
        self._trace_ctx = None  # ambient span captured at worker start
                                # (ISSUE 14 cross-thread propagation)
        self._batch_nbytes = None   # first delivered batch's exact
                                    # bytes (ISSUE 15 memory honesty)

    # -- sharding -------------------------------------------------------
    def _leaf_sharding(self, x):
        if self._sharding is not None:
            return self._sharding(x) if callable(self._sharding) \
                else self._sharding
        if self._mesh is None:
            return None
        from ..parallel.mesh import batch_sharding
        return batch_sharding(self._mesh, getattr(x, "ndim", 0),
                              batch_axis=self._batch_axis,
                              data_axis=self._data_axis)

    def _put_leaf(self, x):
        import jax
        raw = x.data if isinstance(x, NDArray) else x
        if not hasattr(raw, "ndim"):       # scalars, bucket keys, ...
            return x
        sharding = self._leaf_sharding(raw)
        if sharding is None:
            dev = jax.device_put(raw)
        else:
            dev = jax.device_put(raw, sharding)
        return NDArray(dev)

    def _nbytes(self, x):
        raw = x.data if isinstance(x, NDArray) else x
        return getattr(raw, "nbytes", 0)

    def _transfer(self, item):
        if not self._to_device:
            # host-only prefetch (legacy io.PrefetchingIter semantics):
            # the worker's time-in-source is still the decode stat
            return item, 0
        from . import DataBatch

        def rec(obj):
            if isinstance(obj, DataBatch):
                return DataBatch(
                    data=None if obj.data is None else
                    [self._put_leaf(d) for d in obj.data],
                    label=None if obj.label is None else
                    [self._put_leaf(l) for l in obj.label],
                    pad=obj.pad, index=obj.index,
                    bucket_key=obj.bucket_key,
                    provide_data=obj.provide_data,
                    provide_label=obj.provide_label)
            if isinstance(obj, (list, tuple)):
                return type(obj)(rec(o) for o in obj)
            return self._put_leaf(obj)

        def leaves(obj):
            if isinstance(obj, DataBatch):
                for part in (obj.data or []) + (obj.label or []):
                    yield part
            elif isinstance(obj, (list, tuple)):
                for o in obj:
                    yield from leaves(o)
            else:
                yield obj

        nbytes = sum(self._nbytes(l) for l in leaves(item))
        out = rec(item)
        # block in THIS (worker) thread: the consumer must never pay the
        # transfer latency, and the timing below stays honest
        for leaf in leaves(out):
            if isinstance(leaf, NDArray):
                try:
                    leaf.data.block_until_ready()
                except AttributeError:
                    pass
        return out, nbytes

    # -- worker ---------------------------------------------------------
    def _enqueue(self, item):
        """put() that stays responsive to stop(); False if stopping."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except _queue.Full:
                continue
        return False

    def _worker(self):
        # spans the worker opens parent under the trace that was
        # ambient when the consumer started it (tracing.capture in
        # _ensure_started) — the prefetcher's decode/h2d stage spans
        # land inside the training trace, not as orphan roots
        with _tracing.activate(self._trace_ctx):
            self._worker_body()

    def _worker_body(self):
        try:
            it = iter(self._source)
            while self._skip > 0:   # set_state replay-skip (sources
                self._skip -= 1     # without their own cursor)
                try:
                    next(it)
                except StopIteration:
                    self._skip = 0
                    break
        except Exception as e:  # noqa: BLE001 — surface in consumer
            self._enqueue(_WorkerFailure(e))
            return
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                self._enqueue(_END)
                return
            except Exception as e:  # noqa: BLE001 — surface in consumer
                self._enqueue(_WorkerFailure(e))
                return
            t1 = time.perf_counter()
            try:
                dev_item, nbytes = self._transfer(item)
            except Exception as e:  # noqa: BLE001 — surface in consumer
                self._enqueue(_WorkerFailure(e))
                return
            t2 = time.perf_counter()
            self.stats.add("decode", t1 - t0)
            self.stats.add("h2d", t2 - t1, nbytes)
            _profiler_span("pipeline:decode", t0, t1)
            _profiler_span("pipeline:h2d", t1, t2)
            if _tracing.enabled():
                _tracing.record("io.decode", t0, t1)
                _tracing.record("io.h2d", t1, t2, bytes=nbytes)
            if not self._enqueue((dev_item,)):
                return

    def _ensure_started(self):
        if self._thread is None and not self._finished:
            self._trace_ctx = _tracing.capture()
            self._queue = _queue.Queue(maxsize=self._depth)
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._worker, name="mxtpu-device-prefetch",
                daemon=True)
            self._thread.start()

    # -- consumer -------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._finished:
            raise StopIteration
        self._ensure_started()
        now = time.perf_counter()
        if self._last_yield is not None:
            self.stats.add("compute", now - self._last_yield)
        try:
            got = self._queue.get(timeout=self._timeout)
        except _queue.Empty:
            self.close()
            raise MXNetError(
                f"DevicePrefetcher: no batch after {self._timeout}s "
                f"(worker stalled or source hung)")
        t_got = time.perf_counter()
        self.stats.add("stall", t_got - now)
        _profiler_span("pipeline:stall", now, t_got)
        if _tracing.enabled():
            _tracing.record("io.wait", now, t_got)
        if _telem.enabled():
            # read-ahead occupancy AFTER this get: depth batches queued
            # = the worker is fully ahead; 0 = the consumer is about to
            # stall on the next call
            _telem.set_gauge("io.prefetch_queue_depth",
                             self._queue.qsize())
            _telem.set_gauge("io.prefetch_depth", self._depth)
        if got is _END:
            self._shutdown()
            raise StopIteration
        if isinstance(got, _WorkerFailure):
            self._shutdown()
            raise got.exc
        if _telem.enabled():
            # memory honesty (ISSUE 15): exact read-ahead buffer bytes
            # (queued batches + the one being handed out), so an OOM
            # post-mortem can name the prefetch pipeline.  Batch size
            # is measured once — the feed is fixed-shape by design.
            if self._batch_nbytes is None:
                self._batch_nbytes = _batch_nbytes(got[0])
            if self._batch_nbytes:
                _telem.set_gauge(
                    "io.prefetch_buffer_bytes",
                    self._batch_nbytes * (self._queue.qsize() + 1))
        self._last_yield = t_got
        self._consumed += 1
        return got[0]

    def next(self):
        return self.__next__()

    def next_k(self, k):
        """Up to ``k`` consecutive batches as a list (the multi-step
        feed: ``DataParallelTrainer.step_multi`` scans them in ONE
        dispatch, ISSUE 6).  The worker keeps prefetching ahead as
        usual, so collecting a window does not drain the pipeline.
        Returns fewer than ``k`` at end-of-stream; raises
        ``StopIteration`` only when not even one batch is left —
        callers flush the partial tail window, they never lose it."""
        if k < 1:
            raise MXNetError("DevicePrefetcher.next_k: k must be >= 1")
        out = []
        for _ in range(int(k)):
            try:
                out.append(self.__next__())
            except StopIteration:
                if out:
                    return out
                raise
        return out

    def windows(self, k):
        """Iterate the stream as lists of up to ``k`` batches (the last
        window may be short) — sugar over :meth:`next_k` for K-step
        training loops."""
        while True:
            try:
                yield self.next_k(k)
            except StopIteration:
                return

    def __len__(self):
        return len(self._source)

    # -- lifecycle ------------------------------------------------------
    def _shutdown(self):
        self._finished = True
        self._stop.set()
        # unblock a worker stuck in put(); queue may hold device arrays
        while self._queue is not None:
            try:
                self._queue.get_nowait()
            except _queue.Empty:
                break
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def close(self):
        """Stop the worker and join it. Idempotent."""
        self._shutdown()
        close = getattr(self._source, "close", None)
        if callable(close):
            try:
                close()
            except Exception:  # noqa: BLE001 — best-effort source cleanup
                pass

    def reset(self):
        """Restart from the source's beginning (source must support
        ``reset``)."""
        self._shutdown()
        reset = getattr(self._source, "reset", None)
        if callable(reset):
            reset()
        self._finished = False
        self._last_yield = None
        self._consumed = 0
        self._skip = 0

    # -- checkpoint cursor protocol -------------------------------------
    @property
    def batches_consumed(self):
        return self._consumed

    def state_dict(self):
        """Resume cursor: batches DELIVERED (not the worker's read-ahead
        position — up to ``depth`` prefetched-but-unconsumed batches must
        be replayed, not skipped).  Includes the source's own cursor when
        it has one."""
        state = {"batches_consumed": self._consumed}
        src_state = getattr(self._source, "state_dict", None)
        if callable(src_state):
            s = src_state()
            if s:
                state["source"] = s
        return state

    def set_state(self, state):
        """Reposition: reset, then either hand the source its own cursor
        (no replay decode) or have the worker skip-replay
        ``batches_consumed`` batches on ITS iterator (never through the
        device stage)."""
        self.reset()
        n = int(state.get("batches_consumed", 0))
        src_set = getattr(self._source, "set_state", None)
        if "source" in state and callable(src_set):
            src_set(state["source"])
            self._skip = 0
        else:
            self._skip = n
        self._consumed = n

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self._stop.set()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


# ---------------------------------------------------------------------------
# AsyncDecodeIter
# ---------------------------------------------------------------------------

#: weakrefs to decode-pool worker threads whose owning pool's
#: ``close()`` HAS run (work cancelled, shutdown signalled) but which
#: may still be finishing one in-flight sample decode.  The tests'
#: thread-leak guard reads this through :func:`closing_thread_idents`
#: to tell "mid-shutdown with a closer" (longer grace) from a genuine
#: leak (no closer ever ran).  Weakrefs, not idents: OS thread idents
#: are REUSED, so a bare-ident set would let a later genuinely-leaked
#: thread inherit a stale entry's grace — and grow forever.
_CLOSING_THREADS = []


def closing_thread_idents():
    """Idents of still-alive threads registered by a pool ``close()``.
    Exited (or collected) threads are pruned on every read, so the
    registry stays bounded and a reused ident never matches."""
    alive, out = [], set()
    for ref in _CLOSING_THREADS:
        t = ref()
        if t is not None and t.is_alive():
            alive.append(ref)
            if t.ident is not None:
                out.add(t.ident)
    _CLOSING_THREADS[:] = alive
    return out


class AsyncDecodeIter:
    """Fan per-sample decode out over ``n_workers`` threads, yield
    in-order batches.

    ``sample_fn(index)`` decodes one sample (any pickling-free value);
    ``order`` is the index sequence; batches of ``batch_size`` samples
    are submitted ``lookahead`` batches ahead of the consumer, so worker
    threads decode batch N+1..N+lookahead while the consumer holds batch
    N.  Sample-level parallelism *within* a batch comes for free from
    the shared pool.

    Exceptions raised by ``sample_fn`` surface at the consumer in batch
    order; ``close()`` cancels pending work and shuts the pool down.
    """

    def __init__(self, sample_fn, order, batch_size, n_workers=4,
                 lookahead=2, drop_last=True):
        from concurrent.futures import ThreadPoolExecutor
        if batch_size < 1:
            raise MXNetError("AsyncDecodeIter: batch_size must be >= 1")
        self._fn = sample_fn
        order = list(order)
        n = len(order) - (len(order) % batch_size if drop_last else 0)
        self._plan = [order[i:i + batch_size]
                      for i in range(0, n, batch_size)]
        self._n_workers = max(1, int(n_workers))
        self._lookahead = max(1, lookahead)
        self._pool = ThreadPoolExecutor(
            max_workers=self._n_workers,
            thread_name_prefix="mxtpu-decode")
        self._pending = []          # FIFO of [futures] per batch
        self._next_submit = 0
        self._closed = False
        self.stats = PipelineStats()

    def _fill(self):
        while self._next_submit < len(self._plan) and \
                len(self._pending) < self._lookahead:
            futs = [self._pool.submit(self._fn, i)
                    for i in self._plan[self._next_submit]]
            self._pending.append(futs)
            self._next_submit += 1

    def __iter__(self):
        return self

    def __len__(self):
        return len(self._plan)

    def __next__(self):
        if self._closed:
            raise StopIteration
        self._fill()
        if not self._pending:
            self.close()
            raise StopIteration
        futs = self._pending.pop(0)
        t0 = time.perf_counter()
        try:
            results = [f.result() for f in futs]
        except BaseException:
            self.close()
            raise
        t1 = time.perf_counter()
        self.stats.add("decode", t1 - t0)
        _profiler_span("pipeline:decode-wait", t0, t1)
        self._fill()       # keep the pool primed while consumer computes
        return results

    def next(self):
        return self.__next__()

    def close(self, timeout_s=10.0):
        if self._closed:
            return
        self._closed = True
        for futs in self._pending:
            for f in futs:
                f.cancel()
        self._pending = []
        # JOIN the pool threads, but with a DEADLINE: the old
        # wait=True shutdown blocked close() (and test teardown) for as
        # long as one wedged sample decode — the known test_real_data
        # teardown flake on a loaded host.  Pending work was cancelled
        # above, so the join normally returns within one in-flight
        # decode; a straggler past the deadline is left to finish on
        # its own, and its ident is registered so the conftest
        # thread-leak guard knows a closer RAN and grants the longer
        # mid-shutdown grace instead of calling it a leak.
        self._pool.shutdown(wait=False, cancel_futures=True)
        threads = [t for t in getattr(self._pool, "_threads", ())
                   if t is not None]
        for t in threads:
            _CLOSING_THREADS.append(weakref.ref(t))
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
