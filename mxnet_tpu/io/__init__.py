"""``mx.io`` — data iterators + the overlapped input pipeline.

Reference: python/mxnet/io/ (NDArrayIter, CSVIter, ImageRecordIter wrapper,
DataBatch, DataDesc) — SURVEY.md §2.2 "mx.io". Used by the Module API and
reference example scripts.

The pipeline layer (``io/prefetch.py``: :class:`DevicePrefetcher`,
:class:`AsyncDecodeIter`) overlaps host decode, H2D transfer, and device
compute — see docs/INPUT_PIPELINE.md.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array, concatenate
from .prefetch import (DevicePrefetcher, AsyncDecodeIter, PipelineStats,
                       default_prefetch_depth)

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter",
           "ResizeIter", "PrefetchingIter", "ImageRecordIter", "MNISTIter",
           "DevicePrefetcher", "AsyncDecodeIter", "PipelineStats"]


class DataDesc:
    def __init__(self, name, shape, dtype="float32", layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype},{self.layout}]"


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise MXNetError("Data must be list of NDArrays")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    # -- checkpoint cursor protocol (docs/FAULT_TOLERANCE.md) -----------
    def state_dict(self):
        """JSON-able resume cursor. Base iterators report nothing; the
        estimator-level (epoch, batch) cursor still covers them via
        skip-ahead replay."""
        return {}

    def set_state(self, state):
        """Restore a :meth:`state_dict` cursor. Unknown keys ignored."""

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    if data is None:
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}{i if i else ''}"
                if len(data) > 1 else default_name: d
                for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if not isinstance(v, NDArray):
            v = array(_np.asarray(v))
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """Iterate over NDArray/numpy data. Reference: io.NDArrayIter
    (pad/discard/roll_over last-batch handling, shuffle)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.num_data = self.data[0][1].shape[0]
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self._cache_idx = None
        # standalone shuffle-cursor restore (PR 4 known gap): keep the
        # UNSHUFFLED arrays and the per-epoch reshuffle seeds, so
        # set_state() can rebuild this exact epoch's order in a fresh
        # process without replaying the global numpy RNG history
        self._base_data = list(self.data)
        self._base_label = list(self.label)
        self._shuffle_seeds = []
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]),
                         str(v.data.dtype)) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]),
                         str(v.data.dtype)) for k, v in self.label]

    def _apply_shuffle(self, seed):
        """Apply ONE epoch's permutation, derived from ``seed`` alone —
        composing with whatever order the arrays already carry (the
        cumulative in-``reset()`` reshuffle semantics, now replayable)."""
        idx = _np.random.RandomState(seed).permutation(self.num_data)
        self.data = [(k, NDArray(v.data[idx])) for k, v in self.data]
        self.label = [(k, NDArray(v.data[idx])) for k, v in self.label]

    def reset(self):
        if self.shuffle:
            # ONE draw from the global stream names this epoch's
            # permutation; the permutation itself comes from a private
            # RandomState(seed).  The estimator resume path still
            # round-trips (checkpointed numpy RNG -> same seed drawn),
            # and a STANDALONE set_state() can now rebuild the order
            # from the saved seed list with no RNG replay at all.
            seed = int(_np.random.randint(0, 2**31 - 1))
            self._shuffle_seeds.append(seed)
            self._apply_shuffle(seed)
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        if self.cursor + self.batch_size <= self.num_data:
            return [v[self.cursor:self.cursor + self.batch_size]
                    for _, v in data_source]
        if self.last_batch_handle == "discard":
            raise StopIteration
        # pad with wrap-around
        pad = self.batch_size - (self.num_data - self.cursor)
        return [concatenate([v[self.cursor:self.num_data], v[0:pad]])
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def state_dict(self):
        """Resume cursor: the batch cursor into this epoch's shuffled
        order PLUS the per-epoch reshuffle seeds — together they make
        the cursor restorable in a fresh process with any global RNG
        state (the PR 4 gap: the order used to reproduce only by
        replaying the checkpointed numpy stream through the estimator's
        epoch re-entry)."""
        return {"cursor": int(self.cursor),
                "shuffle_seeds": list(self._shuffle_seeds)}

    def set_state(self, state):
        seeds = state.get("shuffle_seeds")
        if seeds is not None and [int(s) for s in seeds] != \
                self._shuffle_seeds:
            # rebuild the exact saved order from scratch: base arrays,
            # then every epoch's permutation in sequence (deterministic
            # standalone — no dependence on the global numpy stream)
            self.data = list(self._base_data)
            self.label = list(self._base_label)
            self._shuffle_seeds = []
            for s in seeds:
                self._shuffle_seeds.append(int(s))
                self._apply_shuffle(int(s))
        self.cursor = int(state.get("cursor", -self.batch_size))


class CSVIter(NDArrayIter):
    """Reference: io.CSVIter (native); here: numpy loadtxt + NDArrayIter."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=None,
                 batch_size=1, **kwargs):
        data = _np.loadtxt(data_csv, delimiter=",").reshape(
            (-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",")
            if label_shape:
                label = label.reshape((-1,) + tuple(label_shape))
        super().__init__(data, label, batch_size, **kwargs)


class LibSVMIter(DataIter):
    """Reference: io.LibSVMIter (src/io/iter_libsvm.cc) — sparse
    ``label index:value ...`` rows batched as CSRNDArray data (memory
    O(nnz), the sparse-training input path)."""

    def __init__(self, data_libsvm, data_shape, batch_size=1,
                 label_libsvm=None, label_shape=None, **kwargs):
        super().__init__(batch_size)
        ncol = int(data_shape[0]) if isinstance(data_shape, (tuple, list)) \
            else int(data_shape)
        labels, indptr, indices, values = [], [0], [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    i, _, v = tok.partition(":")
                    idx = int(i)
                    if idx >= ncol:
                        raise MXNetError(
                            f"libsvm feature index {idx} >= data_shape "
                            f"{ncol}")
                    indices.append(idx)
                    values.append(float(v))
                indptr.append(len(indices))
        if label_libsvm is not None:
            # separate label file (reference label_libsvm): one row per
            # data row, dense floats, reshaped to label_shape
            rows = []
            with open(label_libsvm) as f:
                for line in f:
                    if line.strip():
                        rows.append([float(x) for x in line.split()])
            if len(rows) != len(labels):
                raise MXNetError(
                    f"label_libsvm has {len(rows)} rows, data file has "
                    f"{len(labels)}")
            lab = _np.asarray(rows, _np.float32)
            if label_shape:
                lab = lab.reshape((-1,) + tuple(label_shape))
            elif lab.shape[-1] == 1:
                lab = lab.reshape(-1)
            labels = lab
        self._labels = _np.asarray(labels, _np.float32)
        self._indptr = _np.asarray(indptr, _np.int64)
        self._indices = _np.asarray(indices, _np.int64)
        self._values = _np.asarray(values, _np.float32)
        self._ncol = ncol
        self._n = len(labels)
        self._cursor = 0
        self.provide_data = [DataDesc("data", (batch_size, ncol))]
        self.provide_label = [DataDesc("label", (batch_size,))]

    def reset(self):
        self._cursor = 0

    def _rows(self, lo, hi):
        """CSR slice for rows [lo, hi) plus their labels."""
        start, stop = self._indptr[lo], self._indptr[hi]
        return (self._values[start:stop], self._indptr[lo:hi + 1] - start,
                self._indices[start:stop], self._labels[lo:hi])

    def next(self):
        from ..ndarray.sparse import CSRNDArray
        from ..ndarray import array as _nd_array
        if self._cursor >= self._n:
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self._n)
        self._cursor = hi
        pad = self.batch_size - (hi - lo)
        vals, indptr, idx, labs = self._rows(lo, hi)
        if pad:
            # reference iterators pad the trailing batch by wrapping to
            # the file start; DataBatch.pad reports how many to discard
            wvals, windptr, widx, wlabs = self._rows(0, pad)
            vals = _np.concatenate([vals, wvals])
            idx = _np.concatenate([idx, widx])
            indptr = _np.concatenate([indptr,
                                      windptr[1:] + indptr[-1]])
            labs = _np.concatenate([labs, wlabs])
        csr = CSRNDArray(vals, indptr, idx, (self.batch_size, self._ncol))
        return DataBatch(data=[csr], label=[_nd_array(labs)], pad=pad)


class ResizeIter(DataIter):
    """Resize another iterator to size batches/epoch (reference io.ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-prefetch wrapper (reference io.PrefetchingIter).

    Backed by :class:`DevicePrefetcher` in host-only mode: a worker
    thread pulls batch N+1 from the backing iter while the consumer
    holds batch N (the reference's iter_prefetcher.h double buffer).
    """

    def __init__(self, iters, rename_data=None, rename_label=None):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        assert len(iters) == 1, "only one backing iter supported"
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self._pf = DevicePrefetcher(self.iter, depth=2, to_device=False)

    def reset(self):
        self._pf.reset()

    def __iter__(self):
        return self

    def __next__(self):
        return self._pf.next()

    def next(self):
        return self._pf.next()

    def close(self):
        self._pf.close()

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label


class ImageRecordIter(DataIter):
    """Images from a .rec file with decode + augment + batch.

    Reference: native ImageRecordIter (src/io/iter_image_recordio_2.cc).
    Pure-Python path here; the C++ pipeline in src/ accelerates decode."""

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, preprocess_threads=4, path_imgidx=None, **kwargs):
        super().__init__(batch_size)
        from .. import recordio
        from ..gluon.data.dataset import RecordFileDataset
        self._dataset = RecordFileDataset(path_imgrec)
        self._data_shape = tuple(data_shape)
        self._shuffle = shuffle
        self._rand_mirror = rand_mirror
        self._label_width = label_width
        self._mean = _np.array([mean_r, mean_g, mean_b]).reshape(3, 1, 1)
        self._std = _np.array([std_r, std_g, std_b]).reshape(3, 1, 1)
        self._order = _np.arange(len(self._dataset))
        self._pos = 0
        self._shuffle_seeds = []   # per-epoch reshuffle seeds (replayable)
        self._path_imgrec = path_imgrec
        self._n_threads = preprocess_threads
        # Native C++ decode+prefetch pipeline (src/prefetch.cc) when the
        # library is built and the target shape is square RGB.
        from ..utils import native as _native
        c, h, w = self._data_shape
        self._use_native = (_native.available() and c == 3 and h == w)
        self._native_iter = None
        self._async_iter = None   # pure-Python threaded decode fan-out
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._pos = 0
        if self._shuffle:
            # same standalone-restorable scheme as NDArrayIter: ONE
            # global-stream draw names the epoch's permutation, applied
            # from a private RandomState so set_state can replay it
            seed = int(_np.random.randint(0, 2**31 - 1))
            self._shuffle_seeds.append(seed)
            _np.random.RandomState(seed).shuffle(self._order)
        if self._use_native:
            from ..utils import native as _native
            if self._native_iter is None:
                self._native_iter = _native.NativePrefetcher(
                    self._path_imgrec, self._order, self.batch_size,
                    n_threads=self._n_threads, mode="image",
                    edge=self._data_shape[1], label_width=self._label_width)
            else:  # reuse the open mmap'd reader; just reschedule
                self._native_iter.reset(self._order)
        else:
            self._reset_async()

    def _reset_async(self):
        """(Re)build the threaded decode fan-out for the pure-Python path
        so ``preprocess_threads`` is actually honored (it used to be
        accepted and ignored here — the bench's ``decode_threads: 1``).
        Determinism mode keeps decode synchronous: per-sample host RNG
        (rand_mirror) draws must happen in a fixed order."""
        from .. import debug as _debug
        if self._async_iter is not None:
            self._async_iter.close()
            self._async_iter = None
        if self._n_threads > 1 and not _debug.determinism_enabled():
            self._async_iter = AsyncDecodeIter(
                self._decode_sample, self._order, self.batch_size,
                n_workers=self._n_threads, lookahead=2)

    def iter_next(self):
        return self._pos + self.batch_size <= len(self._dataset)

    def state_dict(self):
        """Resume cursor: sample position within this epoch's order,
        plus the per-epoch reshuffle seeds that make the order itself
        restorable in a fresh process (standalone — no dependence on
        the global numpy stream history)."""
        return {"pos": int(self._pos),
                "shuffle_seeds": list(self._shuffle_seeds)}

    def set_state(self, state):
        """Reposition to a :meth:`state_dict` cursor: the next batch
        decoded is the one the interrupted run would have decoded (the
        threaded decode fan-out is rebuilt from the cursor so already-
        consumed samples are not re-decoded)."""
        seeds = state.get("shuffle_seeds")
        if seeds is not None and [int(s) for s in seeds] != \
                self._shuffle_seeds:
            self._order = _np.arange(len(self._dataset))
            self._shuffle_seeds = []
            for s in seeds:
                self._shuffle_seeds.append(int(s))
                _np.random.RandomState(int(s)).shuffle(self._order)
        pos = int(state.get("pos", 0))
        if pos % self.batch_size:
            raise MXNetError(
                f"ImageRecordIter.set_state: pos {pos} is not a batch "
                f"boundary (batch_size {self.batch_size})")
        self._pos = pos
        if self._async_iter is not None:
            self._async_iter.close()
            self._async_iter = None
        if not self._use_native:
            from .. import debug as _debug
            if self._n_threads > 1 and not _debug.determinism_enabled():
                self._async_iter = AsyncDecodeIter(
                    self._decode_sample, self._order[pos:],
                    self.batch_size, n_workers=self._n_threads,
                    lookahead=2)

    def close(self):
        """Shut down the threaded decode fan-out (no leaked workers)."""
        if self._async_iter is not None:
            self._async_iter.close()
            self._async_iter = None

    def _next_native(self):
        batch, labels = next(self._native_iter)  # raises StopIteration at end
        if len(batch) < self.batch_size:
            raise StopIteration
        img = batch.astype("float32").transpose(0, 3, 1, 2)  # NHWC->NCHW
        if self._rand_mirror:
            flip = _np.random.rand(len(img)) < 0.5
            img[flip] = img[flip][..., ::-1]
        img = (img - self._mean[None]) / self._std[None]
        self._pos += self.batch_size
        lab = labels[:, 0] if self._label_width == 1 else labels
        return DataBatch(data=[array(img)], label=[array(lab)], pad=0)

    def _decode_sample(self, ds_idx):
        """Decode + preprocess ONE record (thread-safe: recordio readers
        hand out per-thread file handles, cv2/PIL decode releases the
        GIL).  Same preprocessing as the native pipeline
        (src/prefetch.cc): short-side resize then center crop to exactly
        (h, w)."""
        from .. import recordio, image
        rec = self._dataset[int(ds_idx)]
        header, img_bytes = recordio.unpack(rec)
        img = image.imdecode(img_bytes)
        c, h, w = self._data_shape
        img = image.resize_short(img, min(h, w))
        img, _ = image.center_crop(img, (w, h))
        img = img.asnumpy().astype("float32").transpose(2, 0, 1)
        if self._rand_mirror and _np.random.rand() < 0.5:
            img = img[:, :, ::-1]
        img = (img - self._mean) / self._std
        label = header.label
        return img, float(label if _np.isscalar(label) else label[0])

    def next(self):
        if not self.iter_next():
            raise StopIteration
        if self._use_native:
            return self._next_native()
        if self._async_iter is not None:
            samples = next(self._async_iter)   # in-order batch
        else:
            samples = [self._decode_sample(self._order[i])
                       for i in range(self._pos,
                                      self._pos + self.batch_size)]
        datas = [img for img, _ in samples]
        labels = [lab for _, lab in samples]
        self._pos += self.batch_size
        return DataBatch(data=[array(_np.stack(datas))],
                         label=[array(_np.asarray(labels))], pad=0)


class MNISTIter(NDArrayIter):
    """Reference: native MNISTIter (src/io/iter_mnist.cc)."""

    def __init__(self, image=None, label=None, batch_size=128, shuffle=True,
                 flat=False, **kwargs):
        from ..gluon.data.vision.datasets import MNIST
        train = image is None or "train" in str(image)
        ds = MNIST(train=train)
        data = ds._data.asnumpy().transpose(0, 3, 1, 2)
        if flat:
            data = data.reshape(data.shape[0], -1)
        super().__init__(data, ds._label, batch_size, shuffle=shuffle)
