"""``mx.runtime`` — feature detection + XLA scheduler flag plumbing.

Reference: python/mxnet/runtime.py over src/libinfo.cc feature flags
("CUDA", "CUDNN", "MKLDNN", ...). The TPU rebuild reports its own substrate,
and additionally owns the XLA *latency-hiding scheduler* flags
(:func:`lhs_flags` / ``MXTPU_LHS=1``) that let the compiler sink the
backward-overlapped gradient collectives (parallel/overlap.py, ISSUE 5)
under remaining backprop compute.
"""
from __future__ import annotations

import os

import jax

__all__ = ["Feature", "Features", "feature_list", "lhs_flags",
           "apply_lhs_flags", "steps_per_call"]


def steps_per_call():
    """Training steps lowered into ONE compiled dispatch
    (``MXTPU_STEPS_PER_CALL``, default 1 = today's one-dispatch-per-step
    behavior — the kill switch, same semantics as ``MXTPU_FUSED_STEP``).
    K > 1 makes K-step-capable loops (``estimator.fit`` over a
    ``DataParallelTrainer``, bench.py) drive
    ``DataParallelTrainer.step_multi`` — K steps scanned device-resident
    per host dispatch, so the per-step eager dispatch + program
    re-entry tax is paid once per K steps (arXiv:2011.03641 host-bound
    concurrency ceiling; arXiv:1909.09756 keeps many steps device-
    resident per launch)."""
    from .base import MXNetError
    raw = os.environ.get("MXTPU_STEPS_PER_CALL", "1")
    try:
        k = int(raw)
    except ValueError:
        raise MXNetError(
            f"MXTPU_STEPS_PER_CALL={raw!r}: expected an integer >= 1")
    if k < 1:
        raise MXNetError(
            f"MXTPU_STEPS_PER_CALL must be >= 1, got {k}")
    return k


# The flag set the TPU scaling playbook enables for comm/compute overlap
# (arXiv:2011.03641's "overlap gradient summation with backprop", done by
# the compiler): the latency-hiding scheduler itself plus async lowering
# of the collectives it reorders.  Harmless elsewhere: XLA ignores
# backend-inapplicable flags on CPU/GPU backends.
_LHS_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
)


def lhs_flags():
    """The XLA latency-hiding-scheduler flag strings (tuple).  These let
    XLA launch a bucket's reduce-scatter as soon as its gradients exist
    and hide the wire time under remaining backward compute — the
    compiler half of the backward-overlapped comm pipeline (the graph
    half is the backward-ordered ``zero.BucketPlan``)."""
    return _LHS_FLAGS


def _tpu_backend_plausible(env):
    """True when the process can plausibly initialize a TPU backend.
    The gate matters: CPU/GPU builds of XLA *fatally abort* on unknown
    ``--xla_tpu_*`` flags, so the LHS flags may only go into XLA_FLAGS
    where libtpu will consume them."""
    platforms = env.get("JAX_PLATFORMS", "")
    if "tpu" in platforms:
        return True
    if platforms:            # explicitly pinned elsewhere (cpu, cuda)
        return False
    import importlib.util
    return importlib.util.find_spec("libtpu") is not None


def apply_lhs_flags(env=None, force=False):
    """Append :func:`lhs_flags` to ``XLA_FLAGS`` in ``env`` (default
    ``os.environ``), skipping flags already present.  Must run BEFORE
    the XLA backend initializes (first jax computation) to take effect;
    ``MXTPU_LHS=1`` triggers this automatically at ``import mxnet_tpu``.
    No-op on non-TPU hosts unless ``force=True`` — the flags are
    TPU-backend-specific and a CPU/GPU XLA build aborts on them.
    Returns the resulting ``XLA_FLAGS`` value."""
    env = os.environ if env is None else env
    current = env.get("XLA_FLAGS", "")
    if not force and not _tpu_backend_plausible(env):
        return current
    missing = [f for f in _LHS_FLAGS
               if f.split("=")[0] not in current]
    if missing:
        current = (current + " " + " ".join(missing)).strip()
        env["XLA_FLAGS"] = current
    return current


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self._enabled = enabled

    @property
    def enabled(self):
        return self._enabled

    def __repr__(self):
        return f"[{'✔' if self._enabled else '✖'} {self.name}]"


def _detect():
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    devs = 0
    try:
        devs = len(jax.devices())
    except Exception:
        pass
    feats = {
        "TPU": backend not in ("cpu",),
        "XLA": True,
        "JAX": True,
        "PALLAS": True,
        "BF16": True,
        "INT64_TENSOR_SIZE": True,
        "DIST_KVSTORE": True,
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "MKLDNN": False,
        "OPENCV": _has_cv(),
        "SIGNAL_HANDLER": True,
        "NATIVE_IO": _has_native_io(),
    }
    return {k: Feature(k, v) for k, v in feats.items()}


def _has_cv():
    try:
        import cv2  # noqa: F401
        return True
    except ImportError:
        return False


def _has_native_io():
    try:
        from .utils import native
        return native.available()
    except Exception:
        return False


class Features(dict):
    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, name):
        feat = self.get(name.upper())
        return bool(feat and feat.enabled)

    def __repr__(self):
        return "[" + ", ".join(repr(v) for v in self.values()) + "]"


def feature_list():
    return list(Features().values())
