"""``mx.runtime`` — feature detection.

Reference: python/mxnet/runtime.py over src/libinfo.cc feature flags
("CUDA", "CUDNN", "MKLDNN", ...). The TPU rebuild reports its own substrate.
"""
from __future__ import annotations

import jax

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self._enabled = enabled

    @property
    def enabled(self):
        return self._enabled

    def __repr__(self):
        return f"[{'✔' if self._enabled else '✖'} {self.name}]"


def _detect():
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    devs = 0
    try:
        devs = len(jax.devices())
    except Exception:
        pass
    feats = {
        "TPU": backend not in ("cpu",),
        "XLA": True,
        "JAX": True,
        "PALLAS": True,
        "BF16": True,
        "INT64_TENSOR_SIZE": True,
        "DIST_KVSTORE": True,
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "MKLDNN": False,
        "OPENCV": _has_cv(),
        "SIGNAL_HANDLER": True,
        "NATIVE_IO": _has_native_io(),
    }
    return {k: Feature(k, v) for k, v in feats.items()}


def _has_cv():
    try:
        import cv2  # noqa: F401
        return True
    except ImportError:
        return False


def _has_native_io():
    try:
        from .utils import native
        return native.available()
    except Exception:
        return False


class Features(dict):
    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, name):
        feat = self.get(name.upper())
        return bool(feat and feat.enabled)

    def __repr__(self):
        return "[" + ", ".join(repr(v) for v in self.values()) + "]"


def feature_list():
    return list(Features().values())
