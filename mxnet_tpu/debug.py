"""``mx.debug`` — NaN-debugging and determinism switches.

Reference counterparts (SURVEY §5.2 race/debug tooling, §5.6 config flags):

- ``MXNET_ENGINE_TYPE=NaiveEngine`` (serialize the engine to bisect races)
  → ``MXTPU_EAGER=1`` (gluon/block.py: hybridize becomes a no-op).
- NaN hunting (the reference had no first-class switch; users bisected with
  NaiveEngine + per-op checks) → ``MXTPU_DEBUG_NANS=1``: enables
  ``jax.config.jax_debug_nans`` so the first NaN/Inf produced by any
  primitive raises immediately, and the imperative tape re-raises with the
  *framework op name* attached (the jax error only names the primitive).
- ``MXNET_ENFORCE_DETERMINISM=1`` (reject non-deterministic cuDNN algos,
  python/mxnet docs/faq/env_var.md) → ``MXTPU_ENFORCE_DETERMINISM=1``:
  XLA:TPU kernels are deterministic by construction, so the residual
  nondeterminism lives in the *host-side* RNG plumbing. The switch makes
  ``mx.random.seed`` also seed numpy's global RNG (samplers and image
  augmenters draw from it), forces the DataLoader's random transforms onto
  a single thread (thread interleaving otherwise reorders global-RNG
  draws), and turns on ``jax_threefry_partitionable`` so device RNG streams
  are stable across sharding layouts. Like the reference flag, it trades
  input-pipeline throughput for bit-reproducibility.

Both flags are read once at ``import mxnet_tpu`` (they must configure jax
before any computation). ``MXTPU_SEED=<n>`` seeds the global RNG at import
so driver-launched runs are reproducible without code changes.

See also ``mx.lint`` (docs/LINT.md): the static trace-safety analyzer
(rules HB01-HB06, CLI ``tools/mxlint.py``) that catches host-sync /
tensor-branching / retrace-storm patterns *before* any device is
touched, and its runtime complement ``MXTPU_RETRACE_WARN=<n>`` — every
hybridized block counts its jax.jit cache misses and warns once (with
the offending shape/dtype signature) when a block retraces past the
threshold. The flags here diagnose wrong *values*; ``mx.lint``
diagnoses wrong *tracing*.
"""
from __future__ import annotations

import os

__all__ = ["debug_nans_enabled", "determinism_enabled"]

_DEBUG_NANS = os.environ.get("MXTPU_DEBUG_NANS", "") == "1"
# Separate switch: legitimate models carry intentional -inf (attention
# masks, beam-search seeds, max-reduce inits), so inf-checking would
# false-positive on healthy forwards and must be opted into.
_DEBUG_INFS = os.environ.get("MXTPU_DEBUG_INFS", "") == "1"
_DETERMINISM = os.environ.get("MXTPU_ENFORCE_DETERMINISM", "") == "1"


def debug_nans_enabled():
    """True when MXTPU_DEBUG_NANS=1 or MXTPU_DEBUG_INFS=1 was set at
    import (either one routes tape errors through the op-naming path)."""
    return _DEBUG_NANS or _DEBUG_INFS


def determinism_enabled():
    """True when MXTPU_ENFORCE_DETERMINISM=1 was set at import."""
    return _DETERMINISM


def _install():
    """Apply the flags to jax config; called from mxnet_tpu/__init__."""
    if _DEBUG_NANS or _DEBUG_INFS or _DETERMINISM:
        import jax
        if _DEBUG_NANS:
            jax.config.update("jax_debug_nans", True)
        if _DEBUG_INFS:
            jax.config.update("jax_debug_infs", True)   # div-by-zero grads
        if _DETERMINISM:
            jax.config.update("jax_threefry_partitionable", True)


def _seed_from_env():
    """MXTPU_SEED: seed the global RNG at import; called from __init__
    after mx.random exists."""
    s = os.environ.get("MXTPU_SEED", "")
    if s:
        from .ndarray import random as _random
        _random.seed(int(s))
