"""``mx.rtc`` — runtime kernel compilation (reference python/mxnet/rtc.py,
CUDA NVRTC). There is no CUDA on TPU; the supported extension points are
mx.operator.CustomOp (python) and Pallas kernels (mxnet_tpu/ops/). The
entry points below raise with that guidance instead of silently missing.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["CudaModule", "CudaKernel"]

_MSG = ("mx.rtc compiles CUDA source at runtime; this TPU-native build has "
        "no CUDA path. Write custom ops with mx.operator.CustomOp (host "
        "python) or a Pallas TPU kernel (see mxnet_tpu/ops/flash_attention"
        ".py for the pattern).")


class CudaModule:
    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)


class CudaKernel:
    def __init__(self, *args, **kwargs):
        raise MXNetError(_MSG)
