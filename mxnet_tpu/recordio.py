"""``mx.recordio`` — RecordIO file format (pure Python reader/writer).

Reference: python/mxnet/recordio.py + dmlc-core/src/recordio (magic+len
framing) and the IRHeader pack/unpack used by im2rec pipelines. Format
compatible with reference .rec files so existing datasets load unchanged.

A native C++ accelerated reader with prefetch lives in src/ (built via
setup_native.py) and is used automatically when available.
"""
from __future__ import annotations

import ctypes
import os
import struct
import threading

import numpy as _np

from .base import MXNetError
from .lint import racecheck as _racecheck

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_KMAGIC_STRUCT = struct.Struct("<I")
_LREC_STRUCT = struct.Struct("<I")


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(lrec):
    return lrec >> 29, lrec & ((1 << 29) - 1)


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.fid = None
        self.open()

    def open(self):
        # per-thread read handles: seek+read pairs from concurrent decode
        # workers (io.AsyncDecodeIter) must not race on one descriptor.
        # (Re)created FIRST so close()/__del__ always find the lock even
        # when the file open below raises; open() itself runs before any
        # reader thread exists (construction / unpickle / reset), which
        # is the happens-before that makes the bare re-init safe:
        self._tl = threading.local()
        self._tl_handles = []  # mxlint: disable=HB14 -- re-created before reader threads start (happens-before via thread start)
        self._tl_lock = _racecheck.make_lock("MXRecordIO._tl_lock")
        if self.flag == "w":
            self.fid = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fid = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"Invalid flag {self.flag}")
        self.pid = os.getpid()

    def _read_fid(self):
        """File handle private to the calling thread (read mode only).

        The creating thread keeps the original ``self.fid``; every other
        thread gets its own lazily-opened descriptor, closed with the
        reader."""
        if self.writable:
            return self.fid
        fid = getattr(self._tl, "fid", None)
        if fid is None or fid.closed:
            if threading.current_thread() is threading.main_thread() and \
                    self.fid is not None and not self.fid.closed:
                fid = self.fid
            else:
                fid = open(self.uri, "rb")
                with self._tl_lock:
                    self._tl_handles.append(fid)
            self._tl.fid = fid
        return fid

    def close(self):
        if self.fid is not None and not self.fid.closed:
            self.fid.close()
        self.fid = None
        if getattr(self, "_tl_lock", None) is None:
            return      # open() never completed: no reader handles exist
        with self._tl_lock:
            for fid in self._tl_handles:
                if not fid.closed:
                    fid.close()
            self._tl_handles = []

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["fid"] = None
        # thread-local handles cannot pickle; reopened lazily per thread
        d.pop("_tl", None)
        d.pop("_tl_handles", None)
        d.pop("_tl_lock", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.fid.tell()

    def write(self, buf):
        assert self.writable
        self.fid.write(_KMAGIC_STRUCT.pack(_MAGIC))
        self.fid.write(_LREC_STRUCT.pack(_encode_lrec(0, len(buf))))
        self.fid.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.fid.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        return self._read_from(self.fid)

    def _read_from(self, fid):
        """Read one record from ``fid`` (any thread's handle)."""
        header = fid.read(4)
        if len(header) < 4:
            return None
        (magic,) = _KMAGIC_STRUCT.unpack(header)
        if magic != _MAGIC:
            raise MXNetError(f"RecordIO magic mismatch at {fid.tell()}")
        (lrec,) = _LREC_STRUCT.unpack(fid.read(4))
        cflag, length = _decode_lrec(lrec)
        buf = fid.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            fid.read(pad)
        if cflag != 0:
            # multi-part record: keep reading continuation parts
            parts = [buf]
            while cflag in (1, 2):
                (magic,) = _KMAGIC_STRUCT.unpack(fid.read(4))
                (lrec,) = _LREC_STRUCT.unpack(fid.read(4))
                cflag, length = _decode_lrec(lrec)
                parts.append(fid.read(length))
                pad = (4 - length % 4) % 4
                if pad:
                    fid.read(pad)
                if cflag == 3:
                    break
            buf = b"".join(parts)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO supporting random read by key (reference
    MXIndexedRecordIO with .idx sidecar: "key\\tposition" lines)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)

    def close(self):
        if self.writable and self.idx:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.fid.seek(self.idx[idx])

    def read_idx(self, idx):
        """Random read by key — safe to call from multiple threads
        concurrently (each thread seeks its own handle)."""
        fid = self._read_fid()
        fid.seek(self.idx[idx])
        return self._read_from(fid)

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


class IRHeader:
    """Image record header (reference IRHeader namedtuple:
    flag, label, id, id2)."""

    __slots__ = ("flag", "label", "id", "id2")

    def __init__(self, flag, label, id, id2):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2

    def __iter__(self):
        return iter((self.flag, self.label, self.id, self.id2))


_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a (header, bytes) into a record payload (reference
    recordio.pack)."""
    flag, label, id_, id2 = tuple(header)
    if isinstance(label, (list, tuple, _np.ndarray)):
        label_arr = _np.asarray(label, dtype=_np.float32)
        header_bytes = struct.pack(_IR_FORMAT, len(label_arr), 0.0,
                                   int(id_), int(id2))
        return header_bytes + label_arr.tobytes() + s
    header_bytes = struct.pack(_IR_FORMAT, 0, float(label), int(id_),
                               int(id2))
    return header_bytes + s


def unpack(s):
    """Unpack record payload into (IRHeader, bytes)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    from . import image
    buf = image.imencode(img, quality=quality, img_fmt=img_fmt)
    return pack(header, buf)


def unpack_img(s, iscolor=-1):
    from . import image
    header, img_bytes = unpack(s)
    return header, image.imdecode(img_bytes, iscolor).asnumpy()
