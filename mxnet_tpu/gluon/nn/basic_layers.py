"""Gluon basic layers.

Reference: python/mxnet/gluon/nn/basic_layers.py (Dense, Dropout, BatchNorm,
InstanceNorm, LayerNorm, Embedding, Flatten, Lambda, HybridLambda,
Sequential, HybridSequential) and activations.py.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ... import ndarray as nd
from ..block import Block, HybridBlock, record_aux_update
from ..parameter import DeferredInitializationError

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "Swish", "GELU",
           "Identity", "Concatenate", "HybridConcatenate"]


class Sequential(Block):
    """Stacks Blocks sequentially. Reference: nn.Sequential."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)):
                x, *args = x
        return (x,) + tuple(args) if args else x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn(
                f"All children of {type(self).__name__} are HybridBlocks; "
                "consider HybridSequential for the jit fast path.",
                stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stacks HybridBlocks; hybridize() jit-compiles the whole chain.
    Reference: nn.HybridSequential."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)):
                x, *args = x
        return (x,) + tuple(args) if args else x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Fully connected layer: out = act(x . W^T + b); weight is (units,
    in_units) — the reference's layout (nn.Dense over FullyConnected,
    src/operator/nn/fully_connected.cc)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act_type = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None

    def _infer_shape_impl(self, x):
        if self._flatten:
            in_units = int(_np.prod(x.shape[1:]))
        else:
            in_units = x.shape[-1]
        self.weight.shape_updated((self._units, in_units))

    def infer_shape(self, x, *args):
        self._infer_shape_impl(x)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"Dense({shape[1] if shape and len(shape) > 1 else None} -> "
                f"{self._units}, "
                f"{self._act_type if self._act_type else 'linear'})")


class Dropout(HybridBlock):
    """Reference: nn.Dropout over src/operator/nn/dropout.cc."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with running stats as aux (non-grad) state.

    Reference: nn.BatchNorm over src/operator/nn/batch_norm.cc. The running
    mean/var updates are threaded out of jit via record_aux_update (SURVEY.md
    §7 hard parts)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape_updated((channels,))

    def cast(self, dtype):
        if _np.dtype(dtype).name in ("float16",) or str(dtype) == "bfloat16":
            dtype = "float32"  # BN statistics stay fp32 (reference behavior)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import _tape
        training = _tape.is_training() and not self._use_global_stats
        d = x.data
        shape = [1] * d.ndim
        shape[self._axis] = d.shape[self._axis]
        axis = self._axis
        eps = self._epsilon
        scale, center = self._scale, self._center
        import jax.lax as lax

        def fn(dd, gg, bb, m_, v_):
            if training:
                # batch stats computed INSIDE the differentiated function so
                # the d(mean)/dx and d(var)/dx terms are in the gradient
                axes = tuple(i for i in range(dd.ndim) if i != axis)
                m_ = jnp.mean(dd, axis=axes)
                v_ = jnp.var(dd, axis=axes)
            inv = lax.rsqrt(v_.astype(dd.dtype) + eps)
            out = (dd - m_.astype(dd.dtype).reshape(shape)) * inv.reshape(shape)
            if scale:
                out = out * gg.astype(dd.dtype).reshape(shape)
            if center:
                out = out + bb.astype(dd.dtype).reshape(shape)
            return out
        from ...ndarray.ndarray import apply_nary
        out = apply_nary(fn, [x, gamma, beta, running_mean, running_var],
                         name="BatchNorm")
        if training:
            # running-stat update (non-grad aux state); works both in the
            # CachedOp trace (collected + threaded out of jit) and eagerly
            axes = tuple(i for i in range(d.ndim) if i != axis)
            rm, rv = running_mean.data, running_var.data
            mean = jax.lax.stop_gradient(jnp.mean(d, axis=axes))
            var = jax.lax.stop_gradient(jnp.var(d, axis=axes))
            mom = self._momentum
            record_aux_update(self.running_mean,
                              NDArray(mom * rm + (1 - mom) * mean.astype(rm.dtype)))
            record_aux_update(self.running_var,
                              NDArray(mom * rv + (1 - mom) * var.astype(rv.dtype)))
        return out

    def __repr__(self):
        return (f"BatchNorm(axis={self._axis}, momentum={self._momentum}, "
                f"in_channels={self.gamma.shape[0] if self.gamma.shape else None})")


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape_updated((c,))
        self.beta.shape_updated((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    """Reference: nn.LayerNorm over src/operator/nn/layer_norm.cc."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape_updated((c,))
        self.beta.shape_updated((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Reference [≥1.6]: nn.GroupNorm."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape_updated((c,))
        self.beta.shape_updated((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        # single source of the math: the GroupNorm op (ops.py)
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    """Reference: nn.Embedding over the Embedding op
    (src/operator/tensor/indexing_op.cc)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        grad_stype = "row_sparse" if sparse_grad else "default"
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, grad_stype=grad_stype)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return x.flatten()

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function as a Block (reference nn.Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            if not hasattr(nd, function):
                raise MXNetError(f"Function name {function} not found in nd")
            self._func_impl = getattr(nd, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = getattr(function, "__name__", "custom")
        else:
            raise MXNetError("function must be a str or callable")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"Lambda({self._func_name})"


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            if not hasattr(nd, function):
                raise MXNetError(f"Function name {function} not found in nd")
            fname = function
            self._func = lambda F, *args: getattr(F, fname)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = getattr(function, "__name__", "custom")
        else:
            raise MXNetError("function must be a str or callable")

    def hybrid_forward(self, F, *args):
        return self._func(F, *args)

    def __repr__(self):
        return f"HybridLambda({self._func_name})"


# ----------------------------------------------------------------------
# activations (reference: gluon/nn/activations.py)
# ----------------------------------------------------------------------

class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(in_channels,),
                init=alpha_initializer or initializer.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class Identity(HybridBlock):
    """Pass-through block (reference gluon/nn/basic_layers.py Identity) —
    useful as a configurable no-op branch."""

    def hybrid_forward(self, F, x):
        return x


class HybridConcatenate(HybridSequential):
    """Run children on the same input and concat outputs along `axis`
    (reference gluon/nn/basic_layers.py HybridConcatenate/HybridConcurrent)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def hybrid_forward(self, F, x):
        outs = [child(x) for child in self._children.values()]
        return F.concat(*outs, dim=self.axis)


class Concatenate(HybridConcatenate):
    """Imperative alias of HybridConcatenate (reference Concatenate)."""
