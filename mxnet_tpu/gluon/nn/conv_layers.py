"""Gluon convolution & pooling layers.

Reference: python/mxnet/gluon/nn/conv_layers.py (Conv1D/2D/3D,
Conv1D/2D/3DTranspose, Max/Avg/Sum pooling, GlobalPool, ReflectionPad2D).
Layout is NCHW / OIHW like the reference; XLA:TPU internally re-lays out for
the MXU, so we keep the user-facing convention.
"""
from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._op_name = op_name
        self._act_type = activation
        if op_name == "Convolution":
            wshape = (channels, in_channels // groups if in_channels else 0) \
                + tuple(kernel_size)
        else:  # Deconvolution: weight is (in, out//groups, *k)
            wshape = (in_channels if in_channels else 0, channels // groups) \
                + tuple(kernel_size)
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer)
            else:
                self.bias = None

    def infer_shape(self, x, *args):
        in_c = x.shape[1]
        w = list(self.weight.shape)
        if self._op_name == "Convolution":
            w[1] = in_c // self._kwargs["num_group"]
        else:
            w[0] = in_c
        self.weight.shape_updated(tuple(w))

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        out = op(x, weight, bias, **self._kwargs)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}(channels={self._channels}, "
                f"kernel={self._kwargs['kernel']})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 1),
                         _tuplize(strides, 1), _tuplize(padding, 1),
                         _tuplize(dilation, 1), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv2D(_Conv):
    """Reference: nn.Conv2D (src/operator/nn/convolution.cc)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 2),
                         _tuplize(strides, 2), _tuplize(padding, 2),
                         _tuplize(dilation, 2), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 3),
                         _tuplize(strides, 3), _tuplize(padding, 3),
                         _tuplize(dilation, 3), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 1),
                         _tuplize(strides, 1), _tuplize(padding, 1),
                         _tuplize(dilation, 1), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuplize(output_padding, 1), **kwargs)


class Conv2DTranspose(_Conv):
    """Reference: nn.Conv2DTranspose (src/operator/nn/deconvolution.cc)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 2),
                         _tuplize(strides, 2), _tuplize(padding, 2),
                         _tuplize(dilation, 2), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuplize(output_padding, 2), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _tuplize(kernel_size, 3),
                         _tuplize(strides, 3), _tuplize(padding, 3),
                         _tuplize(dilation, 3), groups, layout, in_channels,
                         activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tuplize(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']}, "
                f"padding={self._kwargs['pad']})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_tuplize(pool_size, 1),
                         _tuplize(strides, 1) if strides is not None else None,
                         _tuplize(padding, 1), ceil_mode, False, "max",
                         layout, **kwargs)


class MaxPool2D(_Pooling):
    """Reference: nn.MaxPool2D (src/operator/nn/pooling.cc)."""

    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_tuplize(pool_size, 2),
                         _tuplize(strides, 2) if strides is not None else None,
                         _tuplize(padding, 2), ceil_mode, False, "max",
                         layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_tuplize(pool_size, 3),
                         _tuplize(strides, 3) if strides is not None else None,
                         _tuplize(padding, 3), ceil_mode, False, "max",
                         layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_tuplize(pool_size, 1),
                         _tuplize(strides, 1) if strides is not None else None,
                         _tuplize(padding, 1), ceil_mode, False, "avg",
                         layout, count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuplize(pool_size, 2),
                         _tuplize(strides, 2) if strides is not None else None,
                         _tuplize(padding, 2), ceil_mode, False, "avg",
                         layout, count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_tuplize(pool_size, 3),
                         _tuplize(strides, 3) if strides is not None else None,
                         _tuplize(padding, 3), ceil_mode, False, "avg",
                         layout, count_include_pad, **kwargs)


class _GlobalPool(_Pooling):
    def __init__(self, pool_type, ndim, layout, **kwargs):
        super().__init__((1,) * ndim, (1,) * ndim, (0,) * ndim, False, True,
                         pool_type, layout, **kwargs)


class GlobalMaxPool1D(_GlobalPool):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__("max", 1, layout, **kwargs)


class GlobalMaxPool2D(_GlobalPool):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__("max", 2, layout, **kwargs)


class GlobalMaxPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__("max", 3, layout, **kwargs)


class GlobalAvgPool1D(_GlobalPool):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__("avg", 1, layout, **kwargs)


class GlobalAvgPool2D(_GlobalPool):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__("avg", 2, layout, **kwargs)


class GlobalAvgPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__("avg", 3, layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
