"""Gluon RNN cells.

Reference: python/mxnet/gluon/rnn/rnn_cell.py (RecurrentCell, RNNCell,
LSTMCell, GRUCell, SequentialRNNCell, DropoutCell, ZoneoutCell,
ResidualCell, BidirectionalCell).

Gate math follows the reference exactly (i2h = x·W_i2h^T + b_i2h etc., gate
order i,f,c,o for LSTM; r,z,n for GRU) so reference checkpoints load.
"""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..parameter import Parameter

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = dict(info)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape=tuple(shape) if not isinstance(shape, int)
                               else shape, **info, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd
        axis = layout.find("T")
        batch_axis = layout.find("N")
        batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        seq = nd.split(inputs, num_outputs=length, axis=axis,
                       squeeze_axis=True) if length > 1 else \
            [inputs.squeeze(axis)]
        if not isinstance(seq, list):
            seq = [seq]
        for i in range(length):
            output, states = self(seq[i], states)
            outputs.append(output)
        if valid_length is not None:
            outputs = [nd.stack(*outputs, axis=axis)]
            outputs[0] = nd.SequenceMask(
                outputs[0], sequence_length=valid_length,
                use_sequence_length=True, axis=axis)
            if merge_outputs is False:
                outputs = nd.split(outputs[0], num_outputs=length, axis=axis,
                                   squeeze_axis=True)
        elif merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        if merge_outputs and isinstance(outputs, list) and len(outputs) == 1:
            outputs = outputs[0]
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class HybridRecurrentCell(RecurrentCell):
    pass


class RNNCell(HybridRecurrentCell):
    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_updated((self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]

    def forward(self, inputs, states):
        from ... import ndarray as F
        try:
            params = {name: p.data() for name, p in self._reg_params.items()}
        except Exception:
            self.infer_shape(inputs)
            for p in self._reg_params.values():
                if p._data is None:
                    p._finish_deferred_init()
            params = {name: p.data() for name, p in self._reg_params.items()}
        return self.hybrid_forward(F, inputs, states, **params)

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)


class LSTMCell(RNNCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh",
                 recurrent_activation="sigmoid"):
        HybridRecurrentCell.__init__(self, prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._activation = activation
        self._recurrent_activation = recurrent_activation
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)},
                {"shape": (batch_size, self._hidden_size)}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_updated((4 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.split(gates, num_outputs=4, axis=1)
        in_gate = self._get_activation(F, slice_gates[0],
                                       self._recurrent_activation)
        forget_gate = self._get_activation(F, slice_gates[1],
                                           self._recurrent_activation)
        in_transform = self._get_activation(F, slice_gates[2],
                                            self._activation)
        out_gate = self._get_activation(F, slice_gates[3],
                                        self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class GRUCell(RNNCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        HybridRecurrentCell.__init__(self, prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size)}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape_updated((3 * self._hidden_size, x.shape[-1]))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h_n + reset_gate * h2h_n, act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return _cells_begin_state(self._children.values(), batch_size,
                                  **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, batch_size, **kwargs):
    return sum([c.begin_state(batch_size, **kwargs) for c in cells], [])


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states

    def __call__(self, inputs, states):
        from ... import ndarray as F
        return self.hybrid_forward(F, inputs, states)


class ModifierCell(HybridRecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def __call__(self, inputs, states):
        from ... import ndarray as F
        from ...ndarray import random as _rnd
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        if self.zoneout_outputs > 0:
            mask = _rnd.bernoulli(self.zoneout_outputs,
                                  shape=next_output.shape)
            prev = self._prev_output if self._prev_output is not None else \
                next_output * 0
            next_output = mask * prev + (1 - mask) * next_output
        if self.zoneout_states > 0:
            new_states = []
            for ns, s in zip(next_states, states):
                mask = _rnd.bernoulli(self.zoneout_states, shape=ns.shape)
                new_states.append(mask * s + (1 - mask) * ns)
            next_states = new_states
        self._prev_output = next_output
        return next_output, next_states


class ResidualCell(ModifierCell):
    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(HybridRecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def state_info(self, batch_size=0):
        return _cells_state_info([self.l_cell, self.r_cell], batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return _cells_begin_state([self.l_cell, self.r_cell], batch_size,
                                  **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd
        axis = layout.find("T")
        batch_size = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        n_l = len(self.l_cell.state_info())
        l_out, l_states = self.l_cell.unroll(
            length, inputs, begin_state[:n_l], layout, True,
            valid_length=valid_length)
        rev = nd.SequenceReverse(inputs.swapaxes(0, 1) if axis == 1 else inputs,
                                 sequence_length=valid_length,
                                 use_sequence_length=valid_length is not None)
        if axis == 1:
            rev = rev.swapaxes(0, 1)
        r_out, r_states = self.r_cell.unroll(
            length, rev, begin_state[n_l:], layout, True,
            valid_length=valid_length)
        r_out_rev = nd.SequenceReverse(
            r_out.swapaxes(0, 1) if axis == 1 else r_out,
            sequence_length=valid_length,
            use_sequence_length=valid_length is not None)
        if axis == 1:
            r_out_rev = r_out_rev.swapaxes(0, 1)
        outputs = nd.concat(l_out, r_out_rev, dim=2)
        if merge_outputs is False:
            outputs = nd.split(outputs, num_outputs=length, axis=axis,
                               squeeze_axis=True)
        return outputs, l_states + r_states
