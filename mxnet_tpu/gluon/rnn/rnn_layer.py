"""Fused RNN layers: ``gluon.rnn.RNN / LSTM / GRU``.

Reference: python/mxnet/gluon/rnn/rnn_layer.py over the fused ``RNN`` op
(src/operator/rnn.cc, cuDNN RNN). TPU-native realization: the whole multi-layer
(bi)directional recurrence is ONE lax.scan-based jax function dispatched as a
single tape op — the scan compiles to an XLA while loop with the gate matmuls
batched on the MXU, which is the same "fused kernel" role cuDNN played.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...base import MXNetError
from ...ndarray.ndarray import NDArray, apply_nary
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU", "run_fused_rnn"]


def _cell_step(mode, x_t, states, wih, whh, bih, bhh):
    """One timestep of one direction. Gate order matches the reference
    (LSTM: i,f,c,o ; GRU: r,z,n)."""
    if mode == "rnn_tanh" or mode == "rnn_relu":
        h = states[0]
        act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu
        h_new = act(x_t @ wih.T + bih + h @ whh.T + bhh)
        return h_new, (h_new,)
    if mode == "lstm":
        h, c = states
        gates = x_t @ wih.T + bih + h @ whh.T + bhh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)
    if mode == "gru":
        h = states[0]
        gi = x_t @ wih.T + bih
        gh = h @ whh.T + bhh
        ir, iz, inn = jnp.split(gi, 3, axis=-1)
        hr, hz, hn = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(inn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, (h_new,)
    raise MXNetError(f"unknown rnn mode {mode}")


def run_fused_rnn(mode, data, state_arrs, weights, biases, num_layers,
                  ndir, dropout=0.0, training=False, drop_key=None):
    """The shared multi-layer (bi)directional recurrence core — ONE
    lax.scan per direction. Called by both the gluon fused layer and the
    packed-vector ``nd.RNN`` op, so the two stay equivalent by
    construction (same gate order, dropout placement, carry shapes).

    data: (T, B, I) sequence-major raw jax array. state_arrs: (h0[, c0])
    each (L*ndir, B, H). weights/biases: per layer*dir lists of
    (wih, whh) / (bih, bhh). Returns (out, h_stack[, c_stack]).
    """
    layer_in = data
    h_out, c_out = [], []
    for layer in range(num_layers):
        dir_outs = []
        for d in range(ndir):
            idx = layer * ndir + d
            wih, whh = weights[idx]
            bih, bhh = biases[idx]
            init = tuple(s[idx] for s in state_arrs)
            seq = layer_in if d == 0 else jnp.flip(layer_in, 0)

            def step(carry, x_t, wih=wih, whh=whh, bih=bih, bhh=bhh):
                h_new, new_states = _cell_step(mode, x_t, carry,
                                               wih, whh, bih, bhh)
                return new_states, h_new

            final, out_seq = lax.scan(step, init, seq)
            if d == 1:
                out_seq = jnp.flip(out_seq, 0)
            dir_outs.append(out_seq)
            h_out.append(final[0])
            if mode == "lstm":
                c_out.append(final[1])
        layer_in = dir_outs[0] if ndir == 1 else \
            jnp.concatenate(dir_outs, axis=-1)
        if dropout and training and layer < num_layers - 1:
            keep = jax.random.bernoulli(
                jax.random.fold_in(drop_key, layer),
                1.0 - dropout, layer_in.shape)
            layer_in = jnp.where(keep, layer_in / (1.0 - dropout), 0.0)
    outs = (layer_in, jnp.stack(h_out))
    if mode == "lstm":
        outs = outs + (jnp.stack(c_out),)
    return outs


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for layer in range(num_layers):
                for d in (["l", "r"] if bidirectional else ["l"]):
                    in_sz = ni if layer == 0 else nh * self._dir
                    setattr(self, f"{d}{layer}_i2h_weight", self.params.get(
                        f"{d}{layer}_i2h_weight",
                        shape=(ng * nh, in_sz if in_sz else 0),
                        init=i2h_weight_initializer, allow_deferred_init=True))
                    setattr(self, f"{d}{layer}_h2h_weight", self.params.get(
                        f"{d}{layer}_h2h_weight", shape=(ng * nh, nh),
                        init=h2h_weight_initializer))
                    setattr(self, f"{d}{layer}_i2h_bias", self.params.get(
                        f"{d}{layer}_i2h_bias", shape=(ng * nh,),
                        init=i2h_bias_initializer))
                    setattr(self, f"{d}{layer}_h2h_bias", self.params.get(
                        f"{d}{layer}_h2h_bias", shape=(ng * nh,),
                        init=h2h_bias_initializer))

    def state_info(self, batch_size=0):
        if self._mode == "lstm":
            return [{"shape": (self._num_layers * self._dir, batch_size,
                               self._hidden_size)}] * 2
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size)}]

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd
        func = func or nd.zeros
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def infer_shape(self, x, *args):
        ni = x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for d in (["l", "r"] if self._dir == 2 else ["l"]):
            getattr(self, f"{d}0_i2h_weight").shape_updated((ng * nh, ni))

    def _param_list(self):
        names = []
        for layer in range(self._num_layers):
            for d in (["l", "r"] if self._dir == 2 else ["l"]):
                for part in ("i2h_weight", "h2h_weight", "i2h_bias",
                             "h2h_bias"):
                    names.append(f"{d}{layer}_{part}")
        return names

    def forward(self, inputs, states=None):
        from ... import ndarray as F
        batch_axis = self._layout.find("N")
        batch_size = inputs.shape[batch_axis]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size)
        if isinstance(states, NDArray):
            states = [states]
        try:
            params = [p.data() for name, p in
                      [(n, self._reg_params[n]) for n in self._param_list()]]
        except Exception:
            self.infer_shape(inputs)
            for p in self._reg_params.values():
                if p._data is None:
                    p._finish_deferred_init()
            params = [self._reg_params[n].data() for n in self._param_list()]
        out, out_states = self._fused_forward(inputs, states, params)
        return out if skip_states else (out, out_states)

    def __call__(self, inputs, states=None):
        # the fused lax.scan path is already a single op; CachedOp wrapping
        # adds nothing, so bypass the hybridize machinery
        return self.forward(inputs, states)

    def _fused_forward(self, inputs, states, params):
        mode = self._mode
        layout = self._layout
        num_layers = self._num_layers
        ndir = self._dir
        dropout = self._dropout
        n_states = 2 if mode == "lstm" else 1
        from ... import _tape
        training = _tape.is_training()
        from ...ndarray import random as _rnd
        drop_key = _rnd.next_key() if (dropout and training) else None

        def fn(x, *flat):
            state_arrs = flat[:n_states]
            weight_arrs = flat[n_states:]
            data = x if layout == "TNC" else jnp.swapaxes(x, 0, 1)
            weights = [(weight_arrs[i], weight_arrs[i + 1])
                       for i in range(0, len(weight_arrs), 4)]
            biases = [(weight_arrs[i + 2], weight_arrs[i + 3])
                      for i in range(0, len(weight_arrs), 4)]
            outs = run_fused_rnn(mode, data, state_arrs, weights, biases,
                                 num_layers, ndir, dropout, training,
                                 drop_key)
            out = outs[0] if layout == "TNC" else \
                jnp.swapaxes(outs[0], 0, 1)
            return (out,) + outs[1:]

        n_out = 2 + (1 if mode == "lstm" else 0)
        results = apply_nary(fn, [inputs] + list(states) + params,
                             n_out=n_out, name=f"RNN_{mode}")
        out = results[0]
        out_states = list(results[1:])
        return out, out_states

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    """Reference: gluon.rnn.RNN (Elman, relu/tanh)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)


class LSTM(_RNNLayer):
    """Reference: gluon.rnn.LSTM (fused multi-layer cuDNN path)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)


class GRU(_RNNLayer):
    """Reference: gluon.rnn.GRU."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)
