"""Gluon ``Trainer`` — applies an Optimizer over a ParameterDict.

Reference: python/mxnet/gluon/trainer.py (SURVEY.md §2.2 "Gluon Trainer"):
owns the KVStore, `step(batch_size)` = allreduce_grads + update.

TPU mapping (SURVEY.md §3.2): with kvstore='tpu_sync'/'dist_tpu_sync' the
gradient allreduce is a jitted psum over the mesh data axis executed by the
KVStore facade; the optimizer update itself is a fused jax computation per
parameter (or one fused multi-tensor update via `fuse=True`).
"""
from __future__ import annotations

import os
import warnings

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .. import optimizer as opt
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


def _fused_adapter(optimizer):
    """(kernel_name, hyper, pack, unpack) bridging an eager Optimizer's
    state containers to the functional ``optimizer.fused_rule`` kernels,
    for the donated-jit step path; ``None`` -> optimizer not supported
    (eager per-param path runs instead).

    ``pack(i, state)`` builds the kernel-format pytree from the eager
    state WITHOUT copying (same underlying jax arrays); ``unpack(i,
    state, new_state)`` writes the kernel's outputs back into the eager
    containers so ``save_states``/``load_states`` keep working
    unchanged.
    """
    from .. import optimizer as opt_mod
    t = type(optimizer)
    if t in (opt_mod.SGD, opt_mod.NAG):
        mom = optimizer.momentum

        def pack(i, s):
            return {"mom": s.data} if mom else {}

        def unpack(i, s, ns):
            if mom:
                s._set_data(ns["mom"])
        name = "nag" if t is opt_mod.NAG else "sgd"
        return name, {"momentum": mom}, pack, unpack
    if t in (opt_mod.Adam, opt_mod.AdamW):
        def pack(i, s):
            mean, var = s
            return {"m": mean.data, "v": var.data}

        def unpack(i, s, ns):
            mean, var = s
            mean._set_data(ns["m"])
            var._set_data(ns["v"])
        name = "adamw" if t is opt_mod.AdamW else "adam"
        return (name, {"beta1": optimizer.beta1, "beta2": optimizer.beta2,
                       "epsilon": optimizer.epsilon}, pack, unpack)
    return None


def _fused_aux(optimizer):
    """Per-param host scalar the kernel needs beyond (p, g, s, lr, wd):
    Adam's bias-correction step count (eager Adam passes t-1 and the
    kernel increments — see Adam.update).  Shipped stacked in ONE device
    vector and injected as state key ``aux_key`` inside the trace."""
    from .. import optimizer as opt_mod
    if type(optimizer) in (opt_mod.Adam, opt_mod.AdamW):
        return "t", lambda i: optimizer._index_update_count[i] - 1
    return None, None


def _state_shape_ok(optimizer, state):
    """Phase-1 sanity check that an EXISTING eager state matches what the
    adapter's pack() expects (a loaded/custom state in another layout
    falls back to the exact eager path instead of crashing)."""
    from .. import optimizer as opt_mod
    t = type(optimizer)
    if t in (opt_mod.SGD, opt_mod.NAG):
        return (state is None) == (optimizer.momentum == 0.0) and \
            (state is None or isinstance(state, NDArray))
    if t in (opt_mod.Adam, opt_mod.AdamW):
        return isinstance(state, tuple) and len(state) == 2 and \
            all(isinstance(x, NDArray) for x in state)
    return False


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[key] for key in sorted(list(params.keys()))]
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise MXNetError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._kv_initialized = False
        self._states = {}
        self._update_on_kvstore = update_on_kvstore
        self._fused_jit_cache = {}
        # backward-overlapped gradient communication (ISSUE 5): an
        # OverlapScheduler dispatching per-bucket kvstore rounds from
        # autograd grad-ready hooks; armed in _init_kvstore when the
        # store actually spans workers (MXTPU_OVERLAP_COMM=0 kills it)
        self._overlap = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) != {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None if optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)

    def _init_kvstore(self):
        from .. import kvstore as kvs
        if self._kvstore_type is None or self._kvstore_type is False:
            self._kvstore = None
        elif isinstance(self._kvstore_type, str):
            self._kvstore = kvs.create(self._kvstore_type)
        else:
            self._kvstore = self._kvstore_type
        if self._kvstore is not None:
            for i, p in enumerate(self._params):
                if p._data is not None and p.grad_req != "null":
                    self._kvstore.init(i, p.data())
        from ..parallel import zero as _zero
        if self._kvstore is not None and \
                getattr(self._kvstore, "num_workers", 1) > 1 and \
                _zero.overlap_comm_enabled():
            from ..parallel.overlap import OverlapScheduler
            self._overlap = OverlapScheduler(
                self._params, kvstore=self._kvstore).install()
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _all_reduce_grads(self):
        if self._overlap is not None:
            # buckets whose grads finished during backward already went
            # out (async); this launches stragglers and waits ONLY on
            # the tail bucket.  Reduced grads carry _grad_reduced, so
            # the batched pass below cannot double-count them.
            self._overlap.finish()
        if self._kvstore is None or self._kvstore.num_workers <= 1 and \
                type(self._kvstore).__name__ == "KVStoreLocal":
            return
        # ONE implementation shared with parallel.all_reduce_gradients
        # (they used to be drifting copies): one batched pushpull, the
        # dist store coalesces into BIGARRAY_BOUND buckets, and each
        # accumulated gradient (grad_req='add') is reduced exactly once
        # per cycle — allreduce_grads() then step() can't double-count.
        from ..parallel.data_parallel import all_reduce_gradients
        all_reduce_gradients(self._params, kvstore=self._kvstore)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._all_reduce_grads()

    def step(self, batch_size, ignore_stale_grad=False):
        """rescale grads by 1/batch_size, allreduce, update.
        Reference: Trainer.step."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._all_reduce_grads()
        self._update(ignore_stale_grad)

    def update(self, batch_size, ignore_stale_grad=False):
        """update only (user did allreduce manually)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _sharded_update_mesh(self):
        """Ambient dp mesh for weight-update sharding of the fused step
        (arXiv:1909.09756 — MLPerf's TPU-pod trick): when training under
        ``mesh_scope`` with a dp axis, the group update computes each
        eligible parameter's new value on a 1/N shard per chip (with the
        optimizer state living sharded) and all-gathers the result.
        ``MXTPU_SHARDED_SYNC=0`` kills it; no mesh -> exact old path."""
        from ..parallel.mesh import current_mesh, AXIS_DP
        from ..parallel import zero as _zero
        mesh = current_mesh()
        if mesh is None or AXIS_DP not in mesh.axis_names or \
                mesh.shape[AXIS_DP] <= 1 or not _zero.sharded_sync_enabled():
            return None
        return mesh

    def _get_fused_jit(self, apply_fn, aux_key, key, mesh=None):
        """ONE donated XLA program updating the whole parameter group:
        old params and optimizer state are donated (buffers reused for
        the outputs — no per-step param copy), and XLA fuses the N
        elementwise update chains into one launch.  lr/wd/aux/rescale
        enter as device arrays so hyperparameter and step-count changes
        never retrace.  With ``mesh`` (see :meth:`_sharded_update_mesh`)
        the per-param update is sharded over 'dp' — XLA lowers the
        grad feed into a slice per chip and all-gathers the fresh
        params, the eager-trainer half of the ZeRO-1 pipeline."""
        jitted = self._fused_jit_cache.get(key)
        if jitted is None:
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                from ..parallel.mesh import AXIS_DP
                dp = mesh.shape[AXIS_DP]

                def ws_spec(ndim):
                    return NamedSharding(
                        mesh, P(*([AXIS_DP] + [None] * (ndim - 1))))

                def shardable(x):
                    return getattr(x, "ndim", 0) >= 1 and \
                        x.shape[0] % dp == 0 and x.shape[0] >= dp

            def group_update(params, grads, states, lr_vec, wd_vec,
                             aux_vec, rescale):
                # lr/wd/aux arrive stacked in ONE device array each (one
                # H2D per step however many params there are); the
                # per-param slice is a traced op inside the program
                new_ps, new_ss = [], []
                for j, (p, g, s) in enumerate(zip(params, grads,
                                                  states)):
                    g = g * rescale.astype(g.dtype)
                    if aux_key is not None:
                        s = dict(s)
                        s[aux_key] = aux_vec[j]
                    sharded = mesh is not None and shardable(p)
                    if sharded:
                        p = jax.lax.with_sharding_constraint(
                            p, ws_spec(p.ndim))
                        g = jax.lax.with_sharding_constraint(
                            g, ws_spec(g.ndim))
                        s = {k: jax.lax.with_sharding_constraint(
                                v, ws_spec(v.ndim)) if shardable(v) else v
                             for k, v in s.items()}
                    # scalars cast to the param dtype: the eager path's
                    # python floats promote WEAKLY (bf16 params stay
                    # bf16); strong f32 scalars would widen them
                    np_, ns = apply_fn(p, g, s,
                                       lr_vec[j].astype(p.dtype),
                                       wd_vec[j].astype(p.dtype))
                    if sharded:
                        # all-gather the fresh params; state STAYS
                        # sharded across steps (1/N optimizer HBM)
                        np_ = jax.lax.with_sharding_constraint(
                            np_, NamedSharding(
                                mesh, P(*([None] * np_.ndim))))
                    new_ps.append(np_)
                    new_ss.append(ns)
                return new_ps, new_ss
            jitted = jax.jit(group_update, donate_argnums=(0, 2))
            self._fused_jit_cache[key] = jitted
        return jitted

    def _get_flat_fused_jit(self, name, hyper, clip, aux_key, key):
        """ONE flat-bucket program for the whole parameter group
        (ISSUE 6: the reference's multi_sgd-style multi-tensor update):
        params/grads/state concatenate into single flat f32 views and
        the update runs ONCE over the bucket — on TPU as a single Pallas
        kernel (ops/fused_update.py), elsewhere as one fused XLA chain
        instead of one chain per parameter.  Elementwise math is
        IDENTICAL to the per-param path (same kernel functions over the
        same values), so results are bitwise-equal; qualification
        happens host-side in _fused_jit_update."""
        jitted = self._fused_jit_cache.get(key)
        if jitted is None:
            from ..ops.fused_update import fused_bucket_rule
            _, bucket_apply = fused_bucket_rule(name, clip_gradient=clip,
                                                **hyper)

            def group_update_flat(params, grads, states, lr, wd, aux,
                                  rescale):
                shapes = [p.shape for p in params]
                sizes = [p.size for p in params]
                flat_p = jnp.concatenate([jnp.ravel(p) for p in params])
                flat_g = jnp.concatenate([jnp.ravel(g) for g in grads]) \
                    * rescale
                state = {leaf: jnp.concatenate(
                    [jnp.ravel(s[leaf]) for s in states])
                    for leaf in states[0]}
                if aux_key is not None:
                    state[aux_key] = aux
                new_flat, new_state = bucket_apply(flat_p, flat_g, state,
                                                   lr, wd)
                new_ps, new_ss = [], []
                off = 0
                for sh, n in zip(shapes, sizes):
                    new_ps.append(new_flat[off:off + n].reshape(sh))
                    # vector leaves slice back per param; scalar leaves
                    # (adam's t) are aux-managed and unpack ignores them
                    new_ss.append({
                        leaf: v[off:off + n].reshape(sh)
                        for leaf, v in new_state.items()
                        if getattr(v, "ndim", 0) >= 1})
                    off += n
                return new_ps, new_ss

            jitted = jax.jit(group_update_flat, donate_argnums=(0, 2))
            self._fused_jit_cache[key] = jitted
        return jitted

    def _fused_jit_update(self, ignore_stale_grad):
        """Fused, jitted, donated update for the whole parameter group
        (the Trainer-side half of the overlapped-pipeline tentpole; the
        fully fused fwd/bwd/update lives in parallel.DataParallelTrainer).
        Falls back (returns False) for optimizers without a functional
        kernel, sparse/accumulating grads, multi-precision, or
        unexpected loaded state layouts — the exact eager path then
        runs.  Disable with MXTPU_FUSED_STEP=0.

        When the whole group is uniform (same lr/wd/step count, all f32,
        a flat-able rule) the group collapses further into ONE
        flat-bucket update via :meth:`_get_flat_fused_jit`
        (``MXTPU_FUSED_STEP_FLAT=0`` kills that layer only)."""
        from ..ndarray import sparse as _sp
        optimizer = self._optimizer
        if os.environ.get("MXTPU_FUSED_STEP", "1") == "0" or \
                optimizer.multi_precision:
            return False
        adapter = _fused_adapter(optimizer)
        if adapter is None:
            return False
        name, hyper, pack, unpack = adapter
        # phase 1: qualification only — nothing is mutated, so bailing
        # to the per-param path cannot double-count updates
        idxs, params = [], []
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            if param._data._grad is None or not param._data._grad_fresh:
                if ignore_stale_grad:
                    continue
                return False      # per-param path raises the right error
            if param.grad_req == "add" or \
                    isinstance(param._data._grad, _sp.RowSparseNDArray):
                return False      # sparse/accumulating grads: exact path
            if i in self._states and \
                    not _state_shape_ok(optimizer, self._states[i]):
                return False      # foreign state layout: exact path
            idxs.append(i)
            params.append(param)
        if not idxs:
            return True
        # phase 2: commit — counters/lr/wd evaluated once per param
        # (identical bookkeeping to the eager loop), then one jit call
        for i in idxs:
            optimizer._update_count(i)
            if i not in self._states:
                self._states[i] = optimizer.create_state_multi_precision(
                    i, self._params[i].data())
        lrs = [optimizer._get_lr(i) for i in idxs]
        wds = [optimizer._get_wd(i) for i in idxs]
        lr_vec = jnp.asarray(lrs, jnp.float32)
        wd_vec = jnp.asarray(wds, jnp.float32)
        aux_key, aux_fn = _fused_aux(optimizer)
        auxs = [aux_fn(i) for i in idxs] if aux_fn else [0] * len(idxs)
        aux_vec = jnp.asarray(auxs, jnp.int32)
        pvals = [p._data._data for p in params]
        gvals = [p._data._grad for p in params]
        svals = [pack(i, self._states[i]) for i in idxs]
        mesh = self._sharded_update_mesh()
        # flat-bucket qualification (host-side: lr/wd/aux VALUES are
        # known here): a uniform all-f32 group collapses into one
        # flat update — bitwise the same math, one kernel walk
        flat = (mesh is None and len(idxs) > 1
                and os.environ.get("MXTPU_FUSED_STEP_FLAT", "1") != "0"
                and name in ("sgd", "nag", "adam", "adamw")
                and len(set(map(float, lrs))) == 1
                and len(set(map(float, wds))) == 1
                and len(set(map(int, auxs))) == 1
                and all(v.dtype == jnp.float32 for v in pvals)
                and all(g.dtype == jnp.float32 for g in gvals))
        if mesh is not None:
            # values committed off-mesh (fresh eager backward grads,
            # first-step params/state) conflict with the in-program
            # sharding constraints; re-place them replicated on the
            # mesh.  Leaves already living on the mesh — params and the
            # dp-sharded state after step 1 — pass through untouched, so
            # the steady state pays one device_put for the grads only.
            from jax.sharding import NamedSharding, PartitionSpec as _P
            rep = NamedSharding(mesh, _P())

            def _place(x):
                sh = getattr(x, "sharding", None)
                if isinstance(sh, NamedSharding) and sh.mesh == mesh:
                    return x
                return jax.device_put(x, rep)

            orig_shardings = [v.sharding for v in pvals]
            pvals = [_place(v) for v in pvals]
            gvals = [_place(v) for v in gvals]
            svals = [{k: _place(v) for k, v in s.items()} for s in svals]
        key = (name, tuple(sorted(hyper.items())),
               optimizer.clip_gradient, aux_key,
               tuple((v.shape, str(v.dtype)) for v in pvals),
               tuple(tuple(sorted(s)) for s in svals),
               None if mesh is None else tuple(sorted(mesh.shape.items())),
               "flat" if flat else "per-param")
        rescale = jnp.asarray(optimizer.rescale_grad, jnp.float32)
        with warnings.catch_warnings():
            # donation is a TPU/GPU optimization; CPU ignores it with a
            # UserWarning that would spam every step
            warnings.filterwarnings("ignore", message=".*[Dd]onat")
            if flat:
                jitted = self._get_flat_fused_jit(
                    name, hyper, optimizer.clip_gradient, aux_key, key)
                new_ps, new_ss = jitted(
                    pvals, gvals, svals,
                    jnp.asarray(lrs[0], jnp.float32),
                    jnp.asarray(wds[0], jnp.float32),
                    jnp.asarray(auxs[0], jnp.int32), rescale)
            else:
                _, apply_fn = opt.fused_rule(
                    name, clip_gradient=optimizer.clip_gradient, **hyper)
                jitted = self._get_fused_jit(apply_fn, aux_key, key,
                                             mesh=mesh)
                try:
                    new_ps, new_ss = jitted(pvals, gvals, svals, lr_vec,
                                            wd_vec, aux_vec, rescale)
                except Exception:  # noqa: BLE001 — sharded lowering can
                    # fail (e.g. values committed to an incompatible
                    # device set); the replicated program is always
                    # valid. Lowering failures happen before buffers are
                    # donated.
                    if mesh is None:
                        raise
                    jitted = self._get_fused_jit(apply_fn, aux_key,
                                                 key + ("replicated",))
                    new_ps, new_ss = jitted(pvals, gvals, svals, lr_vec,
                                            wd_vec, aux_vec, rescale)
        if mesh is not None:
            # fresh params return to their pre-update placement so the
            # next eager forward never mixes device sets; only the
            # optimizer state stays resident on the mesh (the 1/N HBM
            # saving lives there, and it re-enters the next update
            # without a transfer)
            new_ps = [jax.device_put(v, sh)
                      for v, sh in zip(new_ps, orig_shardings)]
        for i, param, np_, ns in zip(idxs, params, new_ps, new_ss):
            param._data._set_data(np_)
            unpack(i, self._states[i], ns)
            param._data._grad_fresh = False
        return True

    def _fused_group_update(self, ignore_stale_grad):
        """ONE multi-tensor op for the whole parameter group (reference
        multi_sgd_mom_update, src/operator/optimizer_op.cc): collapses N
        eager dispatches per step into one XLA program. Only the plain
        dense-SGD case qualifies; anything else falls back per-param."""
        from .. import optimizer as opt_mod
        from ..ndarray import sparse as _sp
        from ..ndarray import ops as _ops
        opt = self._optimizer
        if type(opt) is not opt_mod.SGD or opt.multi_precision:
            return False
        # phase 1: qualification only — no optimizer state is touched, so
        # bailing to the per-param path cannot double-count updates
        arrays, idxs = [], []
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            if param._data._grad is None or not param._data._grad_fresh:
                if ignore_stale_grad:
                    continue
                return False      # per-param path raises the right error
            if param.grad_req == "add" or \
                    isinstance(param._data._grad, _sp.RowSparseNDArray):
                return False      # sparse/accumulating grads: exact path
            idxs.append(i)
            arrays.append((param, param.data(), param.grad()))
        if not arrays:
            return True
        # phase 2: commit — counters/lr/wd evaluated once per param
        lrs, wds = [], []
        for i in idxs:
            opt._update_count(i)
            lrs.append(opt._get_lr(i))
            wds.append(opt._get_wd(i))
        if opt.momentum:
            flat = []
            for i, (param, w, g) in zip(idxs, arrays):
                if i not in self._states:
                    self._states[i] = opt.create_state_multi_precision(
                        i, w)
                flat += [w, g, self._states[i]]
            _ops.multi_sgd_mom_update(
                *flat, lrs=lrs, wds=wds, momentum=opt.momentum,
                rescale_grad=opt.rescale_grad,
                clip_gradient=opt.clip_gradient)
        else:
            flat = []
            for param, w, g in arrays:
                flat += [w, g]
            _ops.multi_sgd_update(
                *flat, lrs=lrs, wds=wds, rescale_grad=opt.rescale_grad,
                clip_gradient=opt.clip_gradient)
        for param, _, _ in arrays:
            param._data._grad_fresh = False
        return True

    def _update(self, ignore_stale_grad=False):
        if self._fused_jit_update(ignore_stale_grad):
            return
        if self._fused_group_update(ignore_stale_grad):
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null" or param._data is None:
                continue
            if param._data._grad is None or not param._data._grad_fresh:
                if ignore_stale_grad:
                    continue
                raise MXNetError(
                    f"Gradient of Parameter `{param.name}` has not been "
                    "computed. Call backward first, or set grad_req to "
                    "'null' / use ignore_stale_grad=True.")
            if i not in self._states:
                self._states[i] = self._optimizer.create_state_multi_precision(
                    i, param.data())
            self._optimizer.update_multi_precision(
                i, param.data(), param.grad(), self._states[i])
            param._data._grad_fresh = False
            if param.grad_req == "add":
                param.zero_grad()

    # -- checkpoint protocol (mx.checkpoint.CheckpointManager) ----------
    def _counters(self):
        return {
            "num_update": self._optimizer.num_update,
            "begin_num_update": self._optimizer.begin_num_update,
            "index_update_count": dict(self._optimizer._index_update_count),
        }

    def _set_counters(self, counters):
        self._optimizer.num_update = counters.get("num_update", 0)
        self._optimizer.begin_num_update = counters.get(
            "begin_num_update", 0)
        self._optimizer._index_update_count = {
            int(k): v for k, v
            in counters.get("index_update_count", {}).items()}

    @staticmethod
    def _encode_state(s, key, arrays):
        """JSON-able layout descriptor + flat array dict for one param's
        optimizer state (NDArray leaves, arbitrarily nested tuples —
        multi-precision states nest (inner, master))."""
        if s is None:
            return None
        if isinstance(s, NDArray):
            arrays[key] = s
            return "nd"
        if isinstance(s, tuple):
            return ["tuple", [Trainer._encode_state(x, f"{key}.{j}", arrays)
                              for j, x in enumerate(s)]]
        raise MXNetError(
            f"cannot checkpoint optimizer state leaf of type {type(s)}")

    @staticmethod
    def _decode_state(desc, key, arrays):
        if desc is None:
            return None
        if desc == "nd":
            return arrays[key]
        kind, items = desc
        if kind == "tuple":
            return tuple(Trainer._decode_state(d, f"{key}.{j}", arrays)
                         for j, d in enumerate(items))
        raise MXNetError(f"unknown optimizer state descriptor {desc!r}")

    def state_dict(self):
        """Full trainer state as ``{"arrays": {name: NDArray}, "meta":
        json-able}`` — the CheckpointManager protocol.  Arrays are
        host-materializable whatever their device placement (the
        shard_updates mesh-resident state gathers on D2H), so the saved
        form is dp-independent."""
        arrays = {}
        layout = {}
        for i, s in self._states.items():
            layout[str(i)] = self._encode_state(s, f"opt/{i}", arrays)
        meta = {"kind": "gluon.Trainer",
                "optimizer": type(self._optimizer).__name__,
                "layout": layout, "counters": self._counters()}
        return {"arrays": arrays, "meta": meta}

    def load_state_dict(self, d):
        """Inverse of :meth:`state_dict` onto this (possibly fresh)
        trainer; the fused/sharded update paths re-place restored host
        arrays onto the mesh on their next step."""
        arrays, meta = d["arrays"], d["meta"]
        states = {}
        for k, desc in meta.get("layout", {}).items():
            states[int(k)] = self._decode_state(desc, f"opt/{k}", arrays)
        self._states = states
        self._set_counters(meta.get("counters", {}))

    def save_states(self, fname):
        """Reference: Trainer.save_states (optimizer state incl. update
        counts — Adam/LAMB bias correction and lr schedules depend on them)."""
        import pickle
        updater = opt.Updater(self._optimizer)
        updater.states = dict(self._states)
        counters = {
            "num_update": self._optimizer.num_update,
            "begin_num_update": self._optimizer.begin_num_update,
            "index_update_count": dict(self._optimizer._index_update_count),
        }
        with open(fname, "wb") as f:
            f.write(pickle.dumps({"states": updater.get_states(),
                                  "counters": counters}))

    def load_states(self, fname):
        import pickle
        with open(fname, "rb") as f:
            blob = f.read()
        try:
            payload = pickle.loads(blob)
        except Exception:
            payload = None
        updater = opt.Updater(self._optimizer)
        if isinstance(payload, dict) and "states" in payload:
            updater.set_states(payload["states"])
            counters = payload.get("counters", {})
            self._optimizer.num_update = counters.get("num_update", 0)
            self._optimizer.begin_num_update = counters.get(
                "begin_num_update", 0)
            self._optimizer._index_update_count = dict(
                counters.get("index_update_count", {}))
        else:  # legacy blob: raw updater states
            updater.set_states(blob)
        self._states = dict(updater.states)
