"""Gluon losses.

Reference: python/mxnet/gluon/loss.py (Loss base with weight/batch_axis and
sample_weight support; L2, L1, SigmoidBCE, SoftmaxCE, KLDiv, Huber, Hinge,
SquaredHinge, Logistic, Triplet, CTC, Cosine, PoissonNLL).
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, apply_nary
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CTCLoss", "CosineEmbeddingLoss",
           "PoissonNLLLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{type(self).__name__}(batch_axis={self._batch_axis}, w={self._weight})"

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    r"""0.5 * (pred - label)^2, mean over non-batch axes."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.square(label.reshape(pred.shape) - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return loss.mean(axis=tuple(i for i in range(loss.ndim)
                                    if i != self._batch_axis))


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        loss = F.abs(label.reshape(pred.shape) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(i for i in range(loss.ndim)
                                    if i != self._batch_axis))


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        if not self._from_sigmoid:
            def fn(p, l):
                # max(x,0) - x*z + log(1+exp(-|x|)) — numerically stable
                return jnp.maximum(p, 0) - p * l.reshape(p.shape) + \
                    jnp.log1p(jnp.exp(-jnp.abs(p)))
            loss = apply_nary(fn, [pred, label], name="sigmoid_bce")
        else:
            eps = 1e-12
            def fn(p, l):
                l = l.reshape(p.shape)
                return -(jnp.log(p + eps) * l +
                         jnp.log(1 - p + eps) * (1 - l))
            loss = apply_nary(fn, [pred, label], name="sigmoid_bce")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(i for i in range(loss.ndim)
                                    if i != self._batch_axis))


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Reference: gluon.loss.SoftmaxCrossEntropyLoss (sparse_label default
    True, axis -1)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        axis = self._axis
        sparse = self._sparse_label
        from_logits = self._from_logits
        def fn(p, l):
            logp = p if from_logits else jax.nn.log_softmax(p, axis=axis)
            if sparse:
                li = l.astype(jnp.int32)
                if li.ndim == logp.ndim:
                    li = li.squeeze(axis)
                picked = jnp.take_along_axis(
                    logp, jnp.expand_dims(li, axis), axis=axis)
                return -picked.squeeze(axis)
            return -jnp.sum(logp * l, axis=axis)
        loss = apply_nary(fn, [pred, label], name="softmax_ce")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(i for i in range(loss.ndim)
                                    if i != self._batch_axis)) \
            if loss.ndim > 1 else loss


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        axis, from_logits = self._axis, self._from_logits
        def fn(p, l):
            logp = p if from_logits else jax.nn.log_softmax(p, axis=axis)
            return jnp.mean(l * (jnp.log(jnp.maximum(l, 1e-12)) - logp),
                            axis=axis)
        loss = apply_nary(fn, [pred, label], name="kldiv")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(i for i in range(loss.ndim)
                                    if i != self._batch_axis)) \
            if loss.ndim > 1 else loss


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        rho = self._rho
        def fn(p, l):
            a = jnp.abs(l.reshape(p.shape) - p)
            return jnp.where(a > rho, a - 0.5 * rho,
                             (0.5 / rho) * jnp.square(a))
        loss = apply_nary(fn, [pred, label], name="huber")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(i for i in range(loss.ndim)
                                    if i != self._batch_axis))


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        m = self._margin
        def fn(p, l):
            return jnp.maximum(m - p * l.reshape(p.shape), 0)
        loss = apply_nary(fn, [pred, label], name="hinge")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(i for i in range(loss.ndim)
                                    if i != self._batch_axis))


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        m = self._margin
        def fn(p, l):
            return jnp.square(jnp.maximum(m - p * l.reshape(p.shape), 0))
        loss = apply_nary(fn, [pred, label], name="sq_hinge")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(i for i in range(loss.ndim)
                                    if i != self._batch_axis))


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError(f"bad label_format {label_format}")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        fmt = self._label_format
        def fn(p, l):
            l = l.reshape(p.shape)
            if fmt == "signed":
                l = (l + 1.0) / 2.0
            return jnp.maximum(p, 0) - p * l + jnp.log1p(jnp.exp(-jnp.abs(p)))
        loss = apply_nary(fn, [pred, label], name="logistic")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(i for i in range(loss.ndim)
                                    if i != self._batch_axis))


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        m = self._margin
        def fn(p, pos, neg):
            d = jnp.sum(jnp.square(pos.reshape(p.shape) - p) -
                        jnp.square(neg.reshape(p.shape) - p),
                        axis=tuple(range(1, p.ndim)))
            return jnp.maximum(d + m, 0)
        loss = apply_nary(fn, [pred, positive, negative], name="triplet")
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification.

    Reference: gluon.loss.CTCLoss over src/operator/contrib/ctc_loss.cc.
    Implemented with the standard alpha-recursion in log space via lax.scan
    (TPU-friendly: static shapes, no host sync). layout TNC default."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        if layout not in ("NTC", "TNC"):
            raise MXNetError(f"bad layout {layout}")
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        layout = self._layout
        def fn(p, l, *opt):
            if layout == "NTC":
                p = jnp.swapaxes(p, 0, 1)  # -> TNC
            T, N, C = p.shape
            logp = jax.nn.log_softmax(p, axis=-1)
            lab = l.astype(jnp.int32)
            L = lab.shape[1]
            pl = opt[0].astype(jnp.int32) if len(opt) > 0 else \
                jnp.full((N,), T, jnp.int32)
            if len(opt) > 1:
                ll = opt[1].astype(jnp.int32)
            else:
                # reference CTCLoss pads variable-length labels with -1;
                # class 0 is the blank so it can never be a real label —
                # counting lab > 0 therefore infers lengths correctly for
                # both -1- and 0-padded label matrices
                ll = jnp.sum(lab > 0, axis=1).astype(jnp.int32)
            # extended label seq with blanks (blank = 0 per MXNet default);
            # padded entries clamp to 0 so gather indices stay in range
            S = 2 * L + 1
            ext = jnp.zeros((N, S), jnp.int32)
            ext = ext.at[:, 1::2].set(jnp.maximum(lab, 0))
            neg_inf = jnp.asarray(-1e30, logp.dtype)
            alpha0 = jnp.full((N, S), neg_inf)
            alpha0 = alpha0.at[:, 0].set(logp[0, :, 0])
            alpha0 = alpha0.at[:, 1].set(
                jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1).squeeze(1))

            same_as_prev2 = jnp.concatenate(
                [jnp.ones((N, 2), bool),
                 ext[:, 2:] == ext[:, :-2]], axis=1)

            def step(alpha, logp_t):
                a_shift1 = jnp.concatenate(
                    [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
                a_shift2 = jnp.concatenate(
                    [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
                a2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
                merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a2)
                emit = jnp.take_along_axis(logp_t, ext, axis=1)
                return merged + emit, None

            def scan_body(carry, t):
                alpha = carry
                new_alpha, _ = step(alpha, logp[t])
                alpha = jnp.where((t < pl)[:, None], new_alpha, alpha)
                return alpha, None

            alpha, _ = lax_scan(scan_body, alpha0, jnp.arange(1, T))
            end_idx = jnp.maximum(2 * ll - 1, 0)   # ll==0 guarded below
            last = jnp.take_along_axis(alpha, end_idx[:, None], axis=1).squeeze(1)
            last_blank = jnp.take_along_axis(alpha, (2 * ll)[:, None],
                                             axis=1).squeeze(1)
            loss = -jnp.logaddexp(last, last_blank)
            # empty target sequence (inferable now that lengths come from
            # the padding): the only valid path is all-blank = alpha[:, 0]
            return jnp.where(ll == 0, -alpha[:, 0], loss)
        inputs = [pred, label]
        if pred_lengths is not None:
            inputs.append(pred_lengths)
        if label_lengths is not None:
            inputs.append(label_lengths)
        loss = apply_nary(fn, inputs, name="ctc")
        return _apply_weighting(F, loss, self._weight, sample_weight)


def lax_scan(f, init, xs):
    from jax import lax
    return lax.scan(f, init, xs)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        m = self._margin
        def fn(a, b, l):
            a2 = a.reshape(a.shape[0], -1)
            b2 = b.reshape(b.shape[0], -1)
            cos = jnp.sum(a2 * b2, axis=1) / (
                jnp.linalg.norm(a2, axis=1) * jnp.linalg.norm(b2, axis=1)
                + 1e-12)
            l = l.reshape(cos.shape)
            return jnp.where(l == 1, 1 - cos, jnp.maximum(cos - m, 0))
        loss = apply_nary(fn, [input1, input2, label], name="cosine")
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None, epsilon=1e-08):
        from_logits = self._from_logits
        full = self._compute_full
        def fn(p, t):
            t = t.reshape(p.shape)
            if from_logits:
                loss = jnp.exp(p) - t * p
            else:
                loss = p - t * jnp.log(p + epsilon)
            if full:
                stirling = t * jnp.log(jnp.maximum(t, 1.0)) - t + \
                    0.5 * jnp.log(2 * _np.pi * jnp.maximum(t, 1.0))
                loss = loss + jnp.where(t > 1, stirling, jnp.zeros_like(t))
            return loss
        loss = apply_nary(fn, [pred, target], name="poisson_nll")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean()
