"""Attention cells for the NLP model zoo.

Reference capability: GluonNLP's attention cells
(gluon-nlp/src/gluonnlp/model/attention_cell.py: DotProductAttentionCell,
MultiHeadAttentionCell) and the fused ``contrib`` transformer ops
(src/operator/contrib/transformer.cc [>=1.6]) — SURVEY.md §2.4/§5.7.

TPU-native: one (B, H, Lq, Lk) einsum pair that XLA maps straight onto the
MXU; the scaled-dot-product core is swappable for the Pallas
flash-attention kernel (``mxnet_tpu.ops.flash_attention``) which never
materializes the (Lq, Lk) score matrix in HBM.
"""
from __future__ import annotations

import math

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["DotProductAttention", "MultiHeadAttention"]


def _masked_softmax(F, scores, mask):
    """scores: (..., Lq, Lk); mask broadcastable, 1=keep 0=drop."""
    if mask is None:
        return F.softmax(scores, axis=-1)
    neg = -1e9 if scores.dtype == "float32" else -1e4
    scores = F.where(mask, scores, F.ones_like(scores) * neg)
    att = F.softmax(scores, axis=-1)
    return att * mask


class DotProductAttention(HybridBlock):
    """Scaled dot-product attention: softmax(QK^T/sqrt(d))V.

    Inputs: query (B, Lq, C), key (B, Lk, C), value (B, Lk, Cv),
    optional mask (B, Lq, Lk). Returns (context, attn_weights).
    """

    def __init__(self, scaled=True, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._scaled = scaled
        with self.name_scope():
            self._dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, query, key, value, mask=None):
        if self._scaled:
            query = query / math.sqrt(query.shape[-1])
        scores = F.batch_dot(query, key, transpose_b=True)
        att = _masked_softmax(F, scores, mask)
        att = self._dropout(att)
        return F.batch_dot(att, value), att


class MultiHeadAttention(HybridBlock):
    """Multi-head attention (BERT/Transformer building block).

    ``use_flash=True`` routes the core through the Pallas flash-attention
    kernel (TPU; falls back to the XLA einsum path when a mask other than
    causal is required or the kernel is unavailable).
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 use_flash=False, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise MXNetError(f"units {units} not divisible by num_heads "
                             f"{num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._use_flash = use_flash
        self._dropout_rate = dropout
        with self.name_scope():
            self.proj_query = nn.Dense(units, flatten=False,
                                       use_bias=use_bias, prefix="query_")
            self.proj_key = nn.Dense(units, flatten=False,
                                     use_bias=use_bias, prefix="key_")
            self.proj_value = nn.Dense(units, flatten=False,
                                       use_bias=use_bias, prefix="value_")
            self.proj_out = nn.Dense(units, flatten=False,
                                     use_bias=use_bias, prefix="out_")
            self._dropout = nn.Dropout(dropout)

    def _split_heads(self, F, x):
        # (B, L, C) -> (B, H, L, C/H)
        b, l, _ = x.shape
        x = F.reshape(x, (b, l, self._num_heads, -1))
        return F.transpose(x, (0, 2, 1, 3))

    def _merge_heads(self, F, x):
        b, h, l, d = x.shape
        return F.reshape(F.transpose(x, (0, 2, 1, 3)), (b, l, h * d))

    def hybrid_forward(self, F, query, key=None, value=None, mask=None,
                       causal=False):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split_heads(F, self.proj_query(query))
        k = self._split_heads(F, self.proj_key(key))
        v = self._split_heads(F, self.proj_value(value))

        from ...._tape import is_training
        flash_ok = (self._use_flash and mask is None and
                    not (is_training() and self._dropout_rate > 0))
        if flash_ok:
            # flash kernel has no attention-dropout; only taken when that
            # matches the XLA path (eval, or dropout disabled)
            from ....ops import flash_attention
            ctx = flash_attention(q, k, v, causal=causal)
        else:
            d = q.shape[-1]
            q = q / math.sqrt(d)
            # (B,H,Lq,d) x (B,H,Lk,d) -> (B,H,Lq,Lk)
            scores = F.linalg_gemm2(q, k, transpose_b=True)
            full_mask = None
            if causal:
                lq, lk = scores.shape[-2], scores.shape[-1]
                rows = F.arange(lq).reshape((lq, 1))
                cols = F.arange(lk).reshape((1, lk))
                full_mask = (rows >= cols).reshape((1, 1, lq, lk))
            if mask is not None:
                m = F.expand_dims(mask, axis=1)  # (B,1,Lq,Lk)
                full_mask = m if full_mask is None else full_mask * m
            att = _masked_softmax(F, scores, full_mask)
            att = self._dropout(att)
            ctx = F.linalg_gemm2(att, v)
        return self.proj_out(self._merge_heads(F, ctx))
