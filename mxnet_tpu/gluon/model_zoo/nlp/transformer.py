"""Transformer encoder-decoder for machine translation.

Reference capability: GluonNLP's transformer
(gluon-nlp/src/gluonnlp/model/transformer.py: TransformerEncoder,
TransformerDecoder, transformer_en_de_512) — SURVEY.md §2.4. Pre-norm
variant is exposed via ``pre_norm=True`` (trains without warmup tricks);
default matches the reference's post-norm.
"""
from __future__ import annotations

import math

import numpy as _np

from ...block import HybridBlock
from ... import nn
from .attention import MultiHeadAttention

__all__ = ["TransformerEncoder", "TransformerDecoder", "TransformerModel",
           "transformer_en_de_512", "positional_encoding"]


def positional_encoding(max_len, units):
    """Sinusoidal table (max_len, units) as a numpy constant."""
    pos = _np.arange(max_len)[:, None]
    dim = _np.arange(units)[None, :]
    angle = pos / _np.power(10000, (2 * (dim // 2)) / units)
    table = _np.where(dim % 2 == 0, _np.sin(angle), _np.cos(angle))
    return table.astype(_np.float32)


class _FFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, pre_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False,
                                  activation="relu", prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.dropout = nn.Dropout(dropout)
            self.layer_norm = nn.LayerNorm()

    def hybrid_forward(self, F, x):
        if self._pre_norm:
            return x + self.dropout(self.ffn_2(self.ffn_1(
                self.layer_norm(x))))
        return self.layer_norm(x + self.dropout(self.ffn_2(self.ffn_1(x))))


class _EncoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads,
                                                dropout=dropout)
            self.dropout = nn.Dropout(dropout)
            self.layer_norm = nn.LayerNorm()
            self.ffn = _FFN(units, hidden_size, dropout, pre_norm)

    def hybrid_forward(self, F, x, mask=None):
        if self._pre_norm:
            h = self.layer_norm(x)
            x = x + self.dropout(self.attention(h, h, h, mask))
        else:
            x = self.layer_norm(x + self.dropout(
                self.attention(x, x, x, mask)))
        return self.ffn(x)


class _DecoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, **kwargs):
        super().__init__(**kwargs)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.self_attention = MultiHeadAttention(units, num_heads,
                                                     dropout=dropout,
                                                     prefix="self_attn_")
            self.inter_attention = MultiHeadAttention(units, num_heads,
                                                      dropout=dropout,
                                                      prefix="inter_attn_")
            self.dropout = nn.Dropout(dropout)
            self.norm_self = nn.LayerNorm()
            self.norm_inter = nn.LayerNorm()
            self.ffn = _FFN(units, hidden_size, dropout, pre_norm)

    def hybrid_forward(self, F, x, mem, self_mask=None, mem_mask=None):
        if self._pre_norm:
            h = self.norm_self(x)
            x = x + self.dropout(self.self_attention(
                h, h, h, self_mask, causal=True))
            h = self.norm_inter(x)
            x = x + self.dropout(self.inter_attention(h, mem, mem, mem_mask))
        else:
            x = self.norm_self(x + self.dropout(self.self_attention(
                x, x, x, self_mask, causal=True)))
            x = self.norm_inter(x + self.dropout(
                self.inter_attention(x, mem, mem, mem_mask)))
        return self.ffn(x)


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, max_length=1024, pre_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        with self.name_scope():
            # HB04 fix: the sinusoidal table is a registered Constant
            # threaded through the trace once, not an F.array re-upload
            # per call
            self.pos_embed = self.params.get_constant(
                "pos", positional_encoding(max_length, units))
            self.dropout = nn.Dropout(dropout)
            self.cells = nn.HybridSequential(prefix="cells_")
            with self.cells.name_scope():
                for i in range(num_layers):
                    self.cells.add(_EncoderCell(units, hidden_size,
                                                num_heads, dropout, pre_norm,
                                                prefix=f"layer{i}_"))
            self.norm = nn.LayerNorm() if pre_norm else None

    def hybrid_forward(self, F, x, mask=None, pos_embed=None):
        seq_len = x.shape[1]
        pos = F.slice_axis(pos_embed, axis=0, begin=0, end=seq_len)
        x = x * math.sqrt(self._units) + \
            pos.astype(x.dtype).reshape((1, seq_len, -1))
        x = self.dropout(x)
        for cell in self.cells._children.values():
            x = cell(x, mask)
        return self.norm(x) if self.norm is not None else x


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers=6, units=512, hidden_size=2048,
                 num_heads=8, dropout=0.1, max_length=1024, pre_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            # HB04 fix: registered Constant, not a per-call F.array upload
            self.pos_embed = self.params.get_constant(
                "pos", positional_encoding(max_length, units))
            self.dropout = nn.Dropout(dropout)
            self.cells = nn.HybridSequential(prefix="cells_")
            with self.cells.name_scope():
                for i in range(num_layers):
                    self.cells.add(_DecoderCell(units, hidden_size,
                                                num_heads, dropout, pre_norm,
                                                prefix=f"layer{i}_"))
            self.norm = nn.LayerNorm() if pre_norm else None

    def hybrid_forward(self, F, x, mem, self_mask=None, mem_mask=None,
                       pos_embed=None):
        seq_len = x.shape[1]
        pos = F.slice_axis(pos_embed, axis=0, begin=0, end=seq_len)
        x = x * math.sqrt(self._units) + \
            pos.astype(x.dtype).reshape((1, seq_len, -1))
        x = self.dropout(x)
        for cell in self.cells._children.values():
            x = cell(x, mem, self_mask, mem_mask)
        return self.norm(x) if self.norm is not None else x


class TransformerModel(HybridBlock):
    """Full seq2seq MT model with tied source/target/output embeddings.
    Reference: gluonnlp TransformerModel (share_embed/tie_weights flags)."""

    def __init__(self, src_vocab_size, tgt_vocab_size=None, num_layers=6,
                 units=512, hidden_size=2048, num_heads=8, dropout=0.1,
                 max_length=1024, share_embed=True, tie_weights=True,
                 pre_norm=False, **kwargs):
        super().__init__(**kwargs)
        tgt_vocab_size = tgt_vocab_size or src_vocab_size
        self._tie_weights = tie_weights
        self._tgt_vocab_size = tgt_vocab_size
        with self.name_scope():
            self.src_embed = nn.Embedding(src_vocab_size, units,
                                          prefix="src_embed_")
            if share_embed and src_vocab_size == tgt_vocab_size:
                self.tgt_embed = self.src_embed
            else:
                self.tgt_embed = nn.Embedding(tgt_vocab_size, units,
                                              prefix="tgt_embed_")
            self.encoder = TransformerEncoder(
                num_layers, units, hidden_size, num_heads, dropout,
                max_length, pre_norm, prefix="enc_")
            self.decoder = TransformerDecoder(
                num_layers, units, hidden_size, num_heads, dropout,
                max_length, pre_norm, prefix="dec_")
            if not tie_weights:
                self.proj = nn.Dense(tgt_vocab_size, flatten=False,
                                     use_bias=False, prefix="proj_")

    def encode(self, src, src_mask=None):
        return self.encoder(self.src_embed(src), src_mask)

    def decode(self, tgt, mem, self_mask=None, mem_mask=None):
        from .... import ndarray as F
        out = self.decoder(self.tgt_embed(tgt), mem, self_mask, mem_mask)
        if self._tie_weights:
            emb = self.tgt_embed.weight.data()
            return F.dot(out, emb, transpose_b=True)
        return self.proj(out)

    def hybrid_forward(self, F, src, tgt, src_valid_length=None):
        src_mask = mem_mask = None
        if src_valid_length is not None:
            lk = src.shape[1]
            steps = F.arange(lk).reshape((1, 1, lk))
            keep = (steps < F.reshape(src_valid_length, (-1, 1, 1)))
            keep = keep.astype("float32")
            src_mask = F.broadcast_to(keep, (src.shape[0], lk, lk))
            mem_mask = F.broadcast_to(keep,
                                      (src.shape[0], tgt.shape[1], lk))
        mem = self.encode(src, src_mask)
        return self.decode(tgt, mem, None, mem_mask)


def transformer_en_de_512(src_vocab_size=36794, tgt_vocab_size=36794,
                          **kwargs):
    """WMT en-de base config. Reference: gluonnlp transformer_en_de_512."""
    return TransformerModel(src_vocab_size, tgt_vocab_size, num_layers=6,
                            units=512, hidden_size=2048, num_heads=8,
                            **kwargs)
