"""Sequence samplers: beam search + sequence sampling.

Reference capability: GluonNLP's BeamSearchSampler / SequenceSampler
(gluon-nlp/src/gluonnlp/model/sequence_sampler.py) — SURVEY.md §2.4
"Transformer MT ... beam search sampler".

TPU-native: the per-step decoder call is jit-compiled by the caller
(hybridized decoder OR a raw ``jax.jit`` step function — see
``step_mode``); the beam bookkeeping (top-k over vocab*beam,
backpointers) is device-side jnp so only the final sequences hit the
host.  The per-token work never pulls logits to the host (mxlint HB11):
token selection runs through the one device-side ``_topk`` path, and
the early-exit all-done check is amortized to every ``sync_every``
steps instead of one host sync per token.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as _np

from ....ndarray.ndarray import NDArray, array

__all__ = ["BeamSearchScorer", "BeamSearchSampler", "SequenceSampler"]


class BeamSearchScorer:
    """Length-penalized log-prob scorer (Google NMT alpha/K rule).
    Reference: gluonnlp BeamSearchScorer."""

    def __init__(self, alpha=1.0, K=5.0):
        self._alpha = alpha
        self._K = K

    def _lp(self, step):
        return ((self._K + step) ** self._alpha) / \
            ((self._K + 1) ** self._alpha)

    def __call__(self, log_probs, scores, step):
        """GNMT rule: total_logprob / lp(length). ``scores`` holds the
        previous step's normalized totals, so un-normalize by lp(step-1)
        before adding this step's log-probs and re-normalizing."""
        prev = self._lp(step - 1) if step > 1 else 1.0
        return (scores[:, None] * prev + log_probs) / self._lp(step)


def _is_compiled_step(decoder):
    """A raw compiled step function (``jax.jit`` output or any callable
    flagged with ``expects_ndarray = False``) takes/returns jax arrays;
    a Gluon decoder takes NDArrays.  jit-wrapped callables carry
    ``.lower``/``.trace`` stage hooks — that is the auto-detection."""
    flag = getattr(decoder, "expects_ndarray", None)
    if flag is not None:
        return not flag
    return hasattr(decoder, "lower") and callable(
        getattr(decoder, "lower"))


class _StepCaller:
    """Normalizes the decoder calling convention once at construction:
    NDArray in/out (Gluon blocks) or jax arrays in/out (compiled step
    functions), so the samplers themselves stay convention-free."""

    def __init__(self, decoder, step_mode="auto"):
        if step_mode not in ("auto", "ndarray", "jax"):
            from ....base import MXNetError
            raise MXNetError(f"step_mode={step_mode!r}: expected "
                             "auto|ndarray|jax")
        self._decoder = decoder
        self._raw = (_is_compiled_step(decoder) if step_mode == "auto"
                     else step_mode == "jax")

    def __call__(self, step_input, states):
        si = step_input if self._raw else NDArray(step_input)
        log_probs, states = self._decoder(si, states)
        lp = log_probs.data if isinstance(log_probs, NDArray) else \
            jnp.asarray(log_probs)
        return lp, states


class BeamSearchSampler:
    """Beam search over a step decoder.

    ``decoder(step_input, states) -> (log_probs, states)`` where
    step_input is (batch*beam,) int ids and log_probs is
    (batch*beam, vocab). States are pytrees of NDArrays/arrays with leading
    batch*beam axis.

    ``step_mode``: "ndarray" (Gluon decoder, step_input arrives as an
    NDArray), "jax" (compiled step function, raw jax arrays), or "auto"
    (detect a ``jax.jit``-wrapped callable).  ``sync_every``: the
    all-beams-done early-exit is checked on the host only every this
    many steps (per-token host syncs serialize decode — mxlint HB11).
    """

    def __init__(self, beam_size, decoder, eos_id, scorer=None,
                 max_length=100, step_mode="auto", sync_every=8):
        self._beam_size = beam_size
        self._decoder = _StepCaller(decoder, step_mode)
        self._eos_id = int(eos_id)
        self._scorer = scorer or BeamSearchScorer()
        self._max_length = max_length
        self._sync_every = max(1, int(sync_every))

    def _tile_states(self, states, beam):
        return _tile_states(states, beam)

    def _reorder(self, states, idx):
        def gather(x):
            d = x.data if isinstance(x, NDArray) else jnp.asarray(x)
            return d[idx]
        return _tree_map(gather, states)

    def __call__(self, inputs, states):
        """inputs: (batch,) first-step ids. Returns (samples, scores,
        valid_lengths): (batch, beam, L), (batch, beam), (batch, beam)."""
        beam = self._beam_size
        ids = inputs.data if isinstance(inputs, NDArray) else \
            jnp.asarray(inputs)
        batch = ids.shape[0]
        step_input = jnp.repeat(ids, beam, axis=0)           # (B*K,)
        states = self._tile_states(states, beam)
        # first beam active, others -inf so step 0 picks from one beam
        scores = jnp.tile(jnp.array([0.0] + [-1e18] * (beam - 1)), (batch,))
        scores = scores.reshape(batch, beam)
        done = jnp.zeros((batch, beam), dtype=bool)
        lengths = jnp.ones((batch, beam), dtype=jnp.int32)
        sequences = [step_input.reshape(batch, beam)]

        for step in range(1, self._max_length + 1):
            lp, states = self._decoder(step_input, states)
            vocab = lp.shape[-1]
            lp = lp.reshape(batch, beam, vocab)
            cand = self._scorer(lp.reshape(batch * beam, vocab),
                                scores.reshape(batch * beam),
                                step).reshape(batch, beam, vocab)
            # finished beams: score is frozen at its finish-time value
            # (only the EOS self-loop carries it forward) — matching the
            # reference sampler, which stops re-normalizing by lp(step)
            # once a hypothesis ends.
            eos_hot = jnp.arange(vocab) == self._eos_id
            frozen = jnp.where(eos_hot[None, None, :], scores[..., None],
                               -1e18)
            cand = jnp.where(done[..., None], frozen, cand)
            cand = cand.reshape(batch, beam * vocab)
            top_scores, top_idx = _topk(cand, beam)
            beam_idx = top_idx // vocab                       # (B, K)
            word_idx = top_idx % vocab
            scores = top_scores
            flat_beam = (jnp.arange(batch)[:, None] * beam +
                         beam_idx).reshape(-1)
            done = done.reshape(-1)[flat_beam].reshape(batch, beam)
            lengths = lengths.reshape(-1)[flat_beam].reshape(batch, beam)
            sequences = [s.reshape(-1)[flat_beam].reshape(batch, beam)
                         for s in sequences]
            states = self._reorder(states, flat_beam)
            step_input = word_idx.reshape(-1)
            sequences.append(word_idx)
            lengths = jnp.where(~done, lengths + 1, lengths)
            done = done | (word_idx == self._eos_id)
            # amortized early exit: ONE host sync per sync_every tokens,
            # not one per token (HB11 discipline)
            if step % self._sync_every == 0 and bool(jnp.all(done)):
                break

        samples = jnp.stack(sequences, axis=-1)              # (B, K, L)
        order = jnp.argsort(-scores, axis=1)
        gather = jnp.take_along_axis
        samples = gather(samples, order[..., None], axis=1)
        scores = gather(scores, order, axis=1)
        lengths = gather(lengths, order, axis=1)
        return NDArray(samples), NDArray(scores), NDArray(lengths)


def _topk(x, k):
    """THE device-side top-k: beam selection and top-k sampling both
    route through this one ``lax.top_k`` — logits never hit the host."""
    import jax
    return jax.lax.top_k(x, k)


def _tile_states(states, beam):
    def tile(x):
        d = x.data if isinstance(x, NDArray) else jnp.asarray(x)
        return jnp.repeat(d, beam, axis=0)
    return _tree_map(tile, states)


def _tree_map(fn, states):
    if isinstance(states, (list, tuple)):
        return type(states)(_tree_map(fn, s) for s in states)
    if isinstance(states, dict):
        return {key: _tree_map(fn, v) for key, v in states.items()}
    return fn(states)


class SequenceSampler:
    """Multinomial sequence sampler with temperature (and optional
    device-side top-k truncation through the shared ``_topk`` path).
    Reference: gluonnlp SequenceSampler.

    Draws come from the global ``mx.random`` stream — snapshot/restore
    via ``random.get_key_data``/``set_key_data`` (PR 4) reproduces a
    sampling run exactly.  ``step_mode``/``sync_every``: see
    BeamSearchSampler.
    """

    def __init__(self, beam_size, decoder, eos_id, max_length=100,
                 temperature=1.0, top_k=0, step_mode="auto",
                 sync_every=8):
        self._beam_size = beam_size
        self._decoder = _StepCaller(decoder, step_mode)
        self._eos_id = int(eos_id)
        self._max_length = max_length
        self._temperature = temperature
        self._top_k = int(top_k)
        self._sync_every = max(1, int(sync_every))

    def __call__(self, inputs, states):
        import jax
        from ....ndarray import random as _rnd
        beam = self._beam_size
        ids = inputs.data if isinstance(inputs, NDArray) else \
            jnp.asarray(inputs)
        batch = ids.shape[0]
        step_input = jnp.repeat(ids, beam, axis=0)
        states = _tile_states(states, beam)
        done = jnp.zeros((batch * beam,), dtype=bool)
        lengths = jnp.ones((batch * beam,), dtype=jnp.int32)
        scores = jnp.zeros((batch * beam,))
        sequences = [step_input]
        for step in range(1, self._max_length + 1):
            lp, states = self._decoder(step_input, states)
            key = _rnd.next_key()
            scaled = lp / self._temperature
            if self._top_k > 0:
                # truncate to the k best ON DEVICE, sample among them,
                # map back to vocab ids — same _topk as beam search
                vals, idx = _topk(scaled, self._top_k)
                pick = jax.random.categorical(key, vals, axis=-1)
                choice = jnp.take_along_axis(
                    idx, pick[:, None], axis=1)[:, 0]
            else:
                choice = jax.random.categorical(key, scaled, axis=-1)
            choice = jnp.where(done, self._eos_id, choice)
            taken = jnp.take_along_axis(lp, choice[:, None],
                                        axis=1).squeeze(1)
            scores = scores + jnp.where(done, 0.0, taken)
            lengths = jnp.where(done, lengths, lengths + 1)
            sequences.append(choice)
            done = done | (choice == self._eos_id)
            step_input = choice
            if step % self._sync_every == 0 and bool(jnp.all(done)):
                break
        samples = jnp.stack(sequences, axis=-1).reshape(
            batch, beam, -1)
        return (NDArray(samples), NDArray(scores.reshape(batch, beam)),
                NDArray(lengths.reshape(batch, beam)))
