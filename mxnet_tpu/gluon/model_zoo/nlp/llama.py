"""Llama-family decoder LM (the BASELINE.json Llama-3-8B stretch config).

No reference counterpart exists (the fork predates Llama; SURVEY.md §2.5
lists TP/SP as new capabilities) — this is the TPU-native flagship decoder:
RMSNorm + RoPE + grouped-query attention + SwiGLU, attention through the
Pallas flash kernel (ops/flash_attention.py), with two scaling hooks:

- tensor parallel: `tensor_parallel=True` swaps QKV/MLP projections for
  ParallelDense (megatron column/row split over the mesh 'tp' axis; XLA
  inserts the all-reduces from the sharding algebra).
- context parallel: `context_parallel=True` routes attention through
  parallel.ring_attention over the mesh 'sp' axis (neighbour ppermute of
  K/V blocks riding the ICI ring) for sequences longer than one chip's HBM;
  `context_parallel="ulysses"` selects the all-to-all head-scatter scheme
  instead (parallel.ulysses — 4 all-to-alls/layer, heads must divide the
  'sp' size; GQA kv repeated after the wire hop).
"""
from __future__ import annotations

import math

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "RMSNorm",
           "llama3_8b", "llama_tiny"]


class LlamaConfig:
    def __init__(self, vocab_size=128256, hidden_size=4096,
                 intermediate_size=14336, num_layers=32, num_heads=32,
                 num_kv_heads=8, max_seq_len=8192, rope_theta=500000.0,
                 rms_eps=1e-5, tie_embeddings=False,
                 tensor_parallel=False, context_parallel=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads
        self.max_seq_len = max_seq_len
        self.rope_theta = rope_theta
        self.rms_eps = rms_eps
        self.tie_embeddings = tie_embeddings
        self.tensor_parallel = tensor_parallel
        self.context_parallel = context_parallel
        if hidden_size % num_heads:
            raise MXNetError("num_heads must divide hidden_size")
        if num_heads % num_kv_heads:
            raise MXNetError("num_kv_heads must divide num_heads")
        self.head_dim = hidden_size // num_heads


def _rms(d, w, eps):
    """Shared RMSNorm math (layer forward AND kv-cache decode — one
    source so the decode parity can't drift)."""
    import jax.numpy as jnp
    # reduce in fp32 for bf16 inputs (standard practice)
    d32 = d.astype(jnp.float32)
    var = jnp.mean(d32 * d32, axis=-1, keepdims=True)
    return (d32 / jnp.sqrt(var + eps)).astype(d.dtype) * w


def _rot_interleaved(u, cos, sin):
    """Shared interleaved-pair RoPE rotation; cos/sin broadcast against
    u[..., 0::2] ((t, d/2) in the forward, (d/2,) at a decode step)."""
    import jax.numpy as jnp
    u1, u2 = u[..., 0::2], u[..., 1::2]
    return jnp.stack([u1 * cos - u2 * sin,
                      u2 * cos + u1 * sin], axis=-1).reshape(u.shape)


# Query rows fed to the cache-attention einsums are padded to this many
# rows: XLA CPU lowers an M=1 batched dot to a gemv whose accumulation
# order differs from the gemm the full forward runs, while every M>=2
# gemm is bitwise row-stable (verified empirically; tests/test_serving.py
# decode-parity gate).  Padding one duplicate row buys bitwise equality
# between single-token decode and the full-forward attention.
_QPAD = 2


def _cache_attention(q, ck, cv, valid, scale):
    """Single-token attention against a KV cache, shared by
    ``LlamaForCausalLM.generate`` and the serving engine
    (``mxnet_tpu.serving``) — one source so decode parity can't drift.

    Mirrors ``ops.flash_attention._scan_forward``'s single-block
    online-softmax op-for-op (same einsum specs, same mask constant,
    same normalization order) so that decode-with-cache logits are
    BITWISE equal to the full forward's last-row logits in fp32.

    q: (B, H, D) current-position queries (already rotated);
    ck/cv: (B, KVH, L, D) cache (unrepeated GQA heads);
    valid: (B, L) bool, True where the cache position participates;
    scale: softmax scale (1/sqrt(D) — multiplied, like the flash path).
    Returns (B, H*D).
    """
    import jax.numpy as jnp
    from ....ops.flash_attention import _NEG_INF
    b, h, d = q.shape
    kvh, L = ck.shape[1], ck.shape[2]
    rep = h // kvh
    kr = jnp.repeat(ck, rep, axis=1).reshape(b * h, L, d)
    vr = jnp.repeat(cv, rep, axis=1).reshape(b * h, L, d)
    q2 = jnp.broadcast_to(q.reshape(b * h, 1, d), (b * h, _QPAD, d))
    s = jnp.einsum("bqd,bkd->bqk", q2, kr,
                   preferred_element_type=jnp.float32) * scale
    vmask = jnp.repeat(valid[:, None, :], h, axis=1).reshape(b * h, 1, L)
    s = jnp.where(vmask, s, _NEG_INF)
    # single-block flash recurrence with the initial carry folded in,
    # matching _scan_forward's first (only) step exactly
    m0 = jnp.full((b * h, _QPAD, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b * h, _QPAD, 1), jnp.float32)
    acc0 = jnp.zeros((b * h, _QPAD, d), jnp.float32)
    m = jnp.maximum(m0, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m)
    alpha = jnp.exp(m0 - m)
    l = l0 * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc0 * alpha + jnp.einsum("bqk,bkd->bqd", p.astype(cv.dtype), vr,
                                    preferred_element_type=jnp.float32)
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return out[:, 0].reshape(b, h * d)


class RMSNorm(HybridBlock):
    """Root-mean-square norm (no mean subtraction, no bias)."""

    def __init__(self, hidden_size, eps=1e-5, **kwargs):
        super().__init__(**kwargs)
        self._eps = eps
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(hidden_size,),
                                          init="ones")

    def hybrid_forward(self, F, x, weight):
        from ....ndarray.ndarray import apply_nary
        eps = self._eps
        return apply_nary(lambda d, w: _rms(d, w, eps), [x, weight],
                          name="rms_norm")


def _dense(units, use_tp, mode, **kw):
    if use_tp:
        from ....parallel.tensor_parallel import ParallelDense
        return ParallelDense(units, parallel_mode=mode, use_bias=False,
                             flatten=False, **kw)
    return nn.Dense(units, use_bias=False, flatten=False, **kw)


class LlamaAttention(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self.cfg = cfg
        h, kvh, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        with self.name_scope():
            self.q_proj = _dense(h * d, cfg.tensor_parallel, "column")
            self.k_proj = _dense(kvh * d, cfg.tensor_parallel, "column")
            self.v_proj = _dense(kvh * d, cfg.tensor_parallel, "column")
            self.o_proj = _dense(cfg.hidden_size, cfg.tensor_parallel, "row")

    def hybrid_forward(self, F, x):
        import jax
        import jax.numpy as jnp
        from ....ndarray.ndarray import apply_nary
        from ....ops.flash_attention import flash_attention
        cfg = self.cfg
        b, t = x.shape[0], x.shape[1]
        h, kvh, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        q = self.q_proj(x)
        k = self.k_proj(x)
        v = self.v_proj(x)
        theta = cfg.rope_theta

        def rope_and_shape(qd, kd, vd, repeat_kv=True):
            qd = qd.reshape(b, t, h, d).transpose(0, 2, 1, 3)
            kd = kd.reshape(b, t, kvh, d).transpose(0, 2, 1, 3)
            vd = vd.reshape(b, t, kvh, d).transpose(0, 2, 1, 3)
            # rotary embeddings
            pos = jnp.arange(t)
            freqs = theta ** (-jnp.arange(0, d, 2) / d)
            ang = pos[:, None] * freqs[None, :]           # (t, d/2)
            cos, sin = jnp.cos(ang), jnp.sin(ang)

            qd = _rot_interleaved(qd, cos, sin)
            kd = _rot_interleaved(kd, cos, sin)
            if repeat_kv:
                # GQA: repeat kv heads (the ulysses path defers this until
                # after its all-to-all so the wire carries only true kv)
                rep = h // kvh
                kd = jnp.repeat(kd, rep, axis=1)
                vd = jnp.repeat(vd, rep, axis=1)
            return qd, kd, vd

        # Context parallelism is a COMPILED feature: ring attention's
        # shard_map only composes with jit tracing (hybridize /
        # DataParallelTrainer / dryrun) or eager inference — the eager
        # imperative tape records ops under jax.vjp, where cross-device
        # resharding is illegal. Under an eager recorded forward we fall
        # back to local flash attention (numerically identical; just not
        # sequence-sharded).
        from .... import _tape
        use_ring = False
        mesh = None
        if cfg.context_parallel:
            from ....parallel import current_mesh
            mesh = current_mesh()
            in_jit_trace = _tape._STATE.trace_depth > 0
            eager_infer = not _tape.is_recording()
            use_ring = (mesh is not None and "sp" in mesh.shape
                        and (in_jit_trace or eager_infer))

        def attn(qd, kd, vd):
            # cfg.context_parallel selects the CP scheme (SURVEY §5.7
            # lists both): "ulysses" = 4 all-to-alls per layer (q/k/v
            # scatter + out gather), bandwidth ~4x activation; ring =
            # S-1 neighbour K/V block hops
            ulysses = use_ring and self.cfg.context_parallel == "ulysses"
            qd, kd, vd = rope_and_shape(qd, kd, vd, repeat_kv=not ulysses)
            if ulysses:
                from ....parallel.ulysses import ulysses_attention
                o = ulysses_attention(qd, kd, vd, mesh, axis_name="sp",
                                      causal=True)
            elif use_ring:
                from ....parallel.ring_attention import ring_attention
                o = ring_attention(qd, kd, vd, mesh, axis_name="sp",
                                   causal=True)
            else:
                o = flash_attention(qd, kd, vd, causal=True)
            if hasattr(o, "data"):
                o = o.data
            return o.transpose(0, 2, 1, 3).reshape(b, t, h * d)

        out = apply_nary(attn, [q, k, v], name="llama_attention")
        return self.o_proj(out)


class LlamaMLP(HybridBlock):
    """SwiGLU feed-forward."""

    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.gate_proj = _dense(cfg.intermediate_size,
                                    cfg.tensor_parallel, "column")
            self.up_proj = _dense(cfg.intermediate_size,
                                  cfg.tensor_parallel, "column")
            self.down_proj = _dense(cfg.hidden_size,
                                    cfg.tensor_parallel, "row")

    def hybrid_forward(self, F, x):
        import jax
        from ....ndarray.ndarray import apply_nary
        gate = self.gate_proj(x)
        up = self.up_proj(x)

        def fn(g, u):
            return jax.nn.silu(g) * u

        return self.down_proj(apply_nary(fn, [gate, up], name="swiglu"))


class LlamaLayer(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.input_norm = RMSNorm(cfg.hidden_size, cfg.rms_eps)
            self.attention = LlamaAttention(cfg)
            self.post_norm = RMSNorm(cfg.hidden_size, cfg.rms_eps)
            self.mlp = LlamaMLP(cfg)

    def hybrid_forward(self, F, x):
        x = x + self.attention(self.input_norm(x))
        return x + self.mlp(self.post_norm(x))


class LlamaModel(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self.cfg = cfg
        with self.name_scope():
            self.embed = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
            self.layers = nn.HybridSequential()
            for _ in range(cfg.num_layers):
                self.layers.add(LlamaLayer(cfg))
            self.norm = RMSNorm(cfg.hidden_size, cfg.rms_eps)

    def hybrid_forward(self, F, tokens):
        x = self.embed(tokens)
        for layer in self.layers:
            x = layer(x)
        return self.norm(x)

    def remat(self, active=True):
        """Per-decoder-layer jax.checkpoint: keep only layer-boundary
        activations in HBM, recompute interiors in backward (the long-
        context memory schedule; composes with the TP/CP shardings)."""
        for layer in self.layers:
            layer.hybridize(active, remat=active)


class LlamaForCausalLM(HybridBlock):
    def __init__(self, cfg, **kwargs):
        super().__init__(**kwargs)
        self.cfg = cfg
        with self.name_scope():
            self.model = LlamaModel(cfg)
            self.lm_head = None if cfg.tie_embeddings else \
                _dense(cfg.vocab_size, cfg.tensor_parallel, "column")

    def hybrid_forward(self, F, tokens):
        import jax.numpy as jnp
        from ....ndarray.ndarray import apply_nary
        x = self.model(tokens)
        if self.lm_head is not None:
            return self.lm_head(x)
        w = self.model.embed.weight.data()

        def fn(d, emb):
            return d @ emb.T

        return apply_nary(fn, [x, w], name="tied_lm_head")

    def fused_ce_loss(self, tokens, targets, block=2048,
                      ignore_index=None):
        """Per-token CE via the blocked fused head
        (ops/blocked_cross_entropy.py): the (B, L, V) logit tensor is
        never materialized — O(B*L*block) activation memory, the
        long-context memory lever on the loss side (remat covers the
        trunk side). Single-path head only: with a column-TP lm_head the
        vocab is sharded and the blocked logsumexp would need a psum per
        block — use the standard logits path there."""
        from ....base import MXNetError
        from ....ndarray.ndarray import apply_nary
        from ....ops.blocked_cross_entropy import \
            fused_linear_cross_entropy as f
        if self.cfg.tensor_parallel:
            raise MXNetError("fused_ce_loss: vocab is column-sharded "
                             "under tensor_parallel; use the logits path")
        import jax.numpy as jnp
        x = self.model(tokens)
        w = (self.model.embed.weight.data() if self.lm_head is None
             else self.lm_head.weight.data())

        def fn(h, wv, t):
            d = h.shape[-1]
            # both storage layouts are (V, d): lm_head Dense and the tied
            # embedding — transpose unconditionally (a layout change
            # fails loudly in the matmul instead of silently sniffing)
            loss = f(h.reshape(-1, d), wv.T,
                     t.reshape(-1).astype(jnp.int32), block=block,
                     ignore_index=ignore_index)
            return loss.reshape(h.shape[:-1])

        return apply_nary(fn, [x, w, targets], name="fused_ce_loss")

    # ------------------------------------------------------------------
    # KV-cache autoregressive decoding
    # ------------------------------------------------------------------
    def _decode_params(self):
        m = self.model
        layers = []
        for layer in m.layers:
            a, f = layer.attention, layer.mlp
            layers.append((layer.input_norm.weight.data().data,
                           a.q_proj.weight.data().data,
                           a.k_proj.weight.data().data,
                           a.v_proj.weight.data().data,
                           a.o_proj.weight.data().data,
                           layer.post_norm.weight.data().data,
                           f.gate_proj.weight.data().data,
                           f.up_proj.weight.data().data,
                           f.down_proj.weight.data().data))
        head = None if self.lm_head is None \
            else self.lm_head.weight.data().data
        return (m.embed.weight.data().data, m.norm.weight.data().data,
                head, layers)

    def decode_weights(self):
        """Public decode-weight pytree: (embed, final_norm, lm_head|None,
        [per-layer (in_norm, q, k, v, o, post_norm, gate, up, down)]) as
        jax arrays.  The serving engine (``mxnet_tpu.serving``) and
        ``generate()`` both consume this — weights are jit ARGUMENTS, never
        baked into executables as constants."""
        return self._decode_params()

    def generate(self, tokens, max_new_tokens, temperature=0.0, seed=0):
        """Autoregressive decode with per-layer KV caches: ONE jitted
        lax.scan over prefill+generation (static shapes — cache length is
        prefix+max_new), a single cache-row dynamic_update_slice per layer
        per step. The inference path the reference era served via repeated
        full forwards; here the step is O(T) attention against the cache
        instead of O(T^2) recompute. Greedy at temperature=0, else
        categorical sampling from logits/temperature.

        tokens: (B, T_prefix) int NDArray; returns (B, T_prefix +
        max_new_tokens) int32 NDArray.
        """
        import jax
        import jax.numpy as jnp
        from jax import lax
        from ....ndarray.ndarray import NDArray, from_jax

        cfg = self.cfg
        if cfg.tensor_parallel:
            raise MXNetError("generate() runs the single-chip decode path; "
                             "TP-sharded models serve through forward()")
        toks = tokens.data.astype(jnp.int32) if isinstance(tokens, NDArray) \
            else jnp.asarray(tokens, jnp.int32)
        b, t_prefix = toks.shape
        if t_prefix == 0:
            raise MXNetError("generate() needs at least one prefix token")
        total = t_prefix + int(max_new_tokens)
        h, kvh, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
        params = self._decode_params()   # pytree: passed as a jit ARGUMENT
        # (weights must not bake into the executable as constants), and the
        # compiled scan is cached per shape/temperature signature
        n_layers = len(params[3])
        theta = cfg.rope_theta
        temp = float(temperature)
        eps = cfg.rms_eps

        def run(params, toks, key):
            emb, norm_w, head_w, layers = params
            freqs = theta ** (-jnp.arange(0, d, 2) / d)

            def step(carry, xs):
                caches_k, caches_v, prev, key = carry
                i, forced = xs
                tok = jnp.where(i < t_prefix, forced, prev)    # (B,)
                x = emb[tok]                                   # (B, hidden)
                pos_mask = (jnp.arange(total) <= i)            # (total,)
                new_k, new_v = [], []
                for li, (in_w, qw, kw, vw, ow, po_w, gw, uw, dw) in \
                        enumerate(layers):
                    hh = _rms(x, in_w, eps)
                    q = (hh @ qw.T).reshape(b, h, d)
                    k = (hh @ kw.T).reshape(b, kvh, d)
                    v = (hh @ vw.T).reshape(b, kvh, d)
                    ang = i * freqs
                    cos, sin = jnp.cos(ang), jnp.sin(ang)
                    q = _rot_interleaved(q, cos, sin)
                    k = _rot_interleaved(k, cos, sin)
                    ck = lax.dynamic_update_slice(
                        caches_k[li], k[:, :, None, :], (0, 0, i, 0))
                    cv = lax.dynamic_update_slice(
                        caches_v[li], v[:, :, None, :], (0, 0, i, 0))
                    new_k.append(ck)
                    new_v.append(cv)
                    valid = jnp.broadcast_to(pos_mask[None, :], (b, total))
                    o = _cache_attention(q, ck, cv, valid,
                                         1.0 / math.sqrt(d))
                    x = x + o @ ow.T
                    y = _rms(x, po_w, eps)
                    x = x + (jax.nn.silu(y @ gw.T) * (y @ uw.T)) @ dw.T
                logits = _rms(x, norm_w, eps) @ (emb.T if head_w is None
                                                 else head_w.T)
                if temp == 0.0:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(
                        sub, logits.astype(jnp.float32) / temp,
                        axis=-1).astype(jnp.int32)
                return (new_k, new_v, nxt, key), nxt

            caches_k = [jnp.zeros((b, kvh, total, d), emb.dtype)
                        for _ in range(n_layers)]
            caches_v = [jnp.zeros((b, kvh, total, d), emb.dtype)
                        for _ in range(n_layers)]
            forced = jnp.concatenate(
                [toks, jnp.zeros((b, total - t_prefix), jnp.int32)], axis=1)
            init = (caches_k, caches_v, jnp.zeros((b,), jnp.int32), key)
            _, outs = lax.scan(step, init,
                               (jnp.arange(total), forced.T))
            # outs[i] = next-token prediction AFTER consuming position i;
            # generated tokens are outs[t_prefix-1 : total-1]
            gen = outs[t_prefix - 1:total - 1].T        # (B, max_new)
            return jnp.concatenate([toks, gen], axis=1)

        sig = (b, t_prefix, total, temp)
        cache = getattr(self, "_gen_jit", None)
        if cache is None:
            cache = self._gen_jit = {}
        if sig not in cache:
            cache[sig] = jax.jit(run)
        return from_jax(cache[sig](params, toks, jax.random.key(seed)))


def llama3_8b(**overrides):
    """Llama-3-8B geometry (BASELINE stretch config)."""
    return LlamaForCausalLM(LlamaConfig(**overrides))


def llama_tiny(**overrides):
    """Tiny config for tests / dryruns."""
    kw = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
              num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128)
    kw.update(overrides)
    return LlamaForCausalLM(LlamaConfig(**kw))
