"""Word-level language models: StandardRNN and AWD-LSTM.

Reference capability: GluonNLP language models
(gluon-nlp/src/gluonnlp/model/language_model.py: StandardRNN, AWDRNN,
awd_lstm_lm_1150, standard_lstm_lm_200/650/1500) and the reference's
example/gluon/word_language_model — SURVEY.md §2.4.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn, rnn

__all__ = ["StandardRNN", "AWDRNN", "standard_lstm_lm_200",
           "standard_lstm_lm_650", "standard_lstm_lm_1500",
           "awd_lstm_lm_1150", "awd_lstm_lm_600"]


def _make_rnn(mode, hidden_size, num_layers, dropout, input_size, prefix):
    if mode == "lstm":
        return rnn.LSTM(hidden_size, num_layers, dropout=dropout,
                        input_size=input_size, prefix=prefix)
    if mode == "gru":
        return rnn.GRU(hidden_size, num_layers, dropout=dropout,
                       input_size=input_size, prefix=prefix)
    if mode in ("rnn_tanh", "rnn_relu"):
        return rnn.RNN(hidden_size, num_layers, dropout=dropout,
                       input_size=input_size,
                       activation=mode.split("_")[1], prefix=prefix)
    raise ValueError(f"unknown RNN mode {mode!r}")


class StandardRNN(HybridBlock):
    """embedding -> stacked LSTM -> (tied) output projection.
    Reference: gluonnlp StandardRNN."""

    def __init__(self, mode="lstm", vocab_size=33278, embed_size=200,
                 hidden_size=200, num_layers=2, dropout=0.5,
                 tie_weights=False, **kwargs):
        super().__init__(**kwargs)
        if tie_weights and embed_size != hidden_size:
            raise ValueError(
                f"Embedding dimension {embed_size} must equal hidden "
                f"dimension {hidden_size} when tie_weights=True")
        self._mode = mode
        self._vocab_size = vocab_size
        self._tie_weights = tie_weights
        with self.name_scope():
            self.embedding = nn.HybridSequential(prefix="embedding_")
            with self.embedding.name_scope():
                self.embedding.add(nn.Embedding(vocab_size, embed_size))
                if dropout:
                    self.embedding.add(nn.Dropout(dropout))
            self.encoder = _make_rnn(mode, hidden_size, num_layers, dropout,
                                     embed_size, prefix="encoder_")
            if not tie_weights:
                # tied case reuses the embedding matrix directly in
                # hybrid_forward (weight tying, reference StandardRNN)
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        prefix="decoder_")

    def begin_state(self, batch_size=1, **kwargs):
        return self.encoder.begin_state(batch_size=batch_size, **kwargs)

    def hybrid_forward(self, F, inputs, begin_state=None):
        """inputs: (seq_len, batch) ids -> (logits (L, B, V), state)."""
        emb = self.embedding(inputs)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=inputs.shape[1])
        out, state = self.encoder(emb, begin_state)
        if self._tie_weights:
            w = self.embedding[0].weight.data()
            logits = F.dot(out, w, transpose_b=True)
        else:
            logits = self.decoder(out)
        return logits, state


class AWDRNN(HybridBlock):
    """AWD-LSTM (Merity et al.). Reference: gluonnlp AWDRNN.

    Per-layer LSTMs: ``hidden_size`` units for all but the last layer, which
    has ``embed_size`` units when ``tie_weights`` (the reference's layout).
    Regularizers, as variational (shared-mask) dropout — XLA-friendly
    static-shape masks broadcast over the shared axes:
      drop_e — word-level embedding dropout (mask shared over the embedding
               axis, zeroing whole word vectors)
      drop_i — input dropout on the embedding output (mask shared over time)
      drop_h — hidden dropout between LSTM layers (mask shared over time)
      dropout — output dropout before the decoder
    ``weight_drop`` (DropConnect on recurrent matrices) is approximated by
    the time-shared drop_h masks; the exact per-matrix Bernoulli drop is not
    representable without retracing per step.
    """

    def __init__(self, mode="lstm", vocab_size=33278, embed_size=400,
                 hidden_size=1150, num_layers=3, tie_weights=True,
                 dropout=0.4, weight_drop=0.5, drop_h=0.2, drop_i=0.65,
                 drop_e=0.1, **kwargs):
        super().__init__(**kwargs)
        self._tie_weights = tie_weights
        self._vocab_size = vocab_size
        with self.name_scope():
            self.embedding = nn.Embedding(vocab_size, embed_size,
                                          prefix="embedding_")
            # (L, B, C): axis 2 shared -> whole word vectors dropped
            self.embedding_dropout = nn.Dropout(drop_e, axes=(2,))
            self.input_dropout = nn.Dropout(drop_i, axes=(0,))
            self.encoders = nn.HybridSequential(prefix="encoders_")
            with self.encoders.name_scope():
                for i in range(num_layers):
                    last = i == num_layers - 1
                    units = embed_size if (last and tie_weights) \
                        else hidden_size
                    in_units = embed_size if i == 0 else hidden_size
                    self.encoders.add(_make_rnn(
                        mode, units, 1, 0.0, in_units, prefix=f"layer{i}_"))
            self.hidden_dropout = nn.Dropout(drop_h, axes=(0,))
            self.output_dropout = nn.Dropout(dropout, axes=(0,))
            if not tie_weights:
                self.decoder = nn.Dense(vocab_size, flatten=False,
                                        prefix="decoder_")

    def begin_state(self, batch_size=1, **kwargs):
        return [enc.begin_state(batch_size=batch_size, **kwargs)
                for enc in self.encoders._children.values()]

    def hybrid_forward(self, F, inputs, begin_state=None):
        """inputs: (seq_len, batch) ids -> (logits (L, B, V), states)."""
        emb = self.input_dropout(self.embedding_dropout(
            self.embedding(inputs)))
        if begin_state is None:
            begin_state = self.begin_state(batch_size=inputs.shape[1])
        out = emb
        states = []
        encoders = list(self.encoders._children.values())
        for i, (enc, st) in enumerate(zip(encoders, begin_state)):
            out, new_st = enc(out, st)
            states.append(new_st)
            if i != len(encoders) - 1:
                out = self.hidden_dropout(out)
        out = self.output_dropout(out)
        if self._tie_weights:
            w = self.embedding.weight.data()
            logits = F.dot(out, w, transpose_b=True)
        else:
            logits = self.decoder(out)
        return logits, states


def standard_lstm_lm_200(vocab_size=33278, **kwargs):
    return StandardRNN("lstm", vocab_size, 200, 200, 2, dropout=0.2,
                       tie_weights=True, **kwargs)


def standard_lstm_lm_650(vocab_size=33278, **kwargs):
    return StandardRNN("lstm", vocab_size, 650, 650, 2, dropout=0.5,
                       tie_weights=True, **kwargs)


def standard_lstm_lm_1500(vocab_size=33278, **kwargs):
    return StandardRNN("lstm", vocab_size, 1500, 1500, 2, dropout=0.65,
                       tie_weights=False, **kwargs)


def awd_lstm_lm_1150(vocab_size=33278, **kwargs):
    return AWDRNN("lstm", vocab_size, 400, 1150, 3, **kwargs)


def awd_lstm_lm_600(vocab_size=33278, **kwargs):
    return AWDRNN("lstm", vocab_size, 200, 600, 3, **kwargs)
