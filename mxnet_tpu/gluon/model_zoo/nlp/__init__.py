"""``gluon.model_zoo.nlp`` — NLP models (GluonNLP capability parity).

Reference: the external GluonNLP package (dmlc/gluon-nlp) listed as a
capability target in SURVEY.md §2.4: BERT (pretrain+finetune), Transformer
MT with beam search, AWD-LSTM/standard LSTM language models, attention
cells.
"""
from .attention import *  # noqa: F401,F403
from .bert import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
from .language_model import *  # noqa: F401,F403
from .sampler import *  # noqa: F401,F403
from .llama import *  # noqa: F401,F403

from . import attention, bert, transformer, language_model, sampler, \
    llama  # noqa

_MODELS = {}
for _m in (bert, transformer, language_model):
    for _name in _m.__all__:
        _fn = getattr(_m, _name)
        # model constructors only: lowercase factories, excluding the
        # parameterized get_* helpers and non-model utilities
        if callable(_fn) and _name[0].islower() and \
                not _name.startswith(("get_", "positional_")):
            _MODELS[_name] = _fn


def get_model(name, pretrained=False, root=None, ctx=None, **kwargs):
    """Reference: gluonnlp.model.get_model(name, pretrained=).

    ``pretrained=True`` resolves weights from the LOCAL model store
    (model_store.get_model_file; zero-egress build, no download)."""
    if name not in _MODELS:
        from ....base import MXNetError
        raise MXNetError(
            f"Model {name!r} is not present in the NLP model zoo; "
            f"available: {sorted(_MODELS)}")
    net = _MODELS[name](**kwargs)
    if pretrained:
        from ..model_store import get_model_file
        net.load_parameters(get_model_file(name, root), ctx=ctx)
    return net
