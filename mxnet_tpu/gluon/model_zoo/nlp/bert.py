"""BERT for the TPU rebuild.

Reference capability: GluonNLP BERT (gluon-nlp/src/gluonnlp/model/bert.py:
BERTEncoder, BERTModel, bert_12_768_12 / bert_24_1024_16 with MLM + NSP
heads) — SURVEY.md §2.4. Built from the same Gluon primitives so it
hybridizes to one XLA program; gelu + layer_norm fuse into the matmuls.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from .attention import MultiHeadAttention

__all__ = ["BERTEncoder", "BERTModel", "get_bert_model", "bert_12_768_12",
           "bert_24_1024_16"]


class _PositionwiseFFN(HybridBlock):
    """ffn(x) = W2 . gelu(W1 . x); reference gluonnlp BERTPositionwiseFFN."""

    def __init__(self, units, hidden_size, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False, prefix="ffn1_")
            self.activation = nn.GELU()
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.dropout = nn.Dropout(dropout)
            self.layer_norm = nn.LayerNorm(epsilon=1e-12)

    def hybrid_forward(self, F, x):
        out = self.ffn_2(self.activation(self.ffn_1(x)))
        return self.layer_norm(x + self.dropout(out))


class _BERTEncoderCell(HybridBlock):
    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 use_flash=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadAttention(units, num_heads,
                                                dropout=dropout,
                                                use_flash=use_flash)
            self.dropout = nn.Dropout(dropout)
            self.layer_norm = nn.LayerNorm(epsilon=1e-12)
            self.ffn = _PositionwiseFFN(units, hidden_size, dropout=dropout)

    def hybrid_forward(self, F, x, mask=None):
        out = self.attention(x, x, x, mask)
        x = self.layer_norm(x + self.dropout(out))
        return self.ffn(x)


class BERTEncoder(HybridBlock):
    """Stack of post-norm transformer encoder cells.
    Reference: gluonnlp BERTEncoder."""

    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, dropout=0.1, max_length=512, use_flash=False,
                 **kwargs):
        super().__init__(**kwargs)
        self._max_length = max_length
        self._units = units
        with self.name_scope():
            self.dropout = nn.Dropout(dropout)
            self.layer_norm = nn.LayerNorm(epsilon=1e-12)
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units),
                init="normal")
            self.transformer_cells = nn.HybridSequential(prefix="cells_")
            with self.transformer_cells.name_scope():
                for i in range(num_layers):
                    self.transformer_cells.add(_BERTEncoderCell(
                        units, hidden_size, num_heads, dropout=dropout,
                        use_flash=use_flash, prefix=f"layer{i}_"))

    def hybrid_forward(self, F, x, mask=None, position_weight=None):
        seq_len = x.shape[1]
        pos = F.slice(position_weight, begin=(0, 0), end=(seq_len, None))
        x = x + F.expand_dims(pos, axis=0)
        x = self.dropout(self.layer_norm(x))
        for cell in self.transformer_cells._children.values():
            x = cell(x, mask)
        return x

    def remat(self, active=True):
        """Per-cell rematerialization: each encoder cell is jitted under
        jax.checkpoint, so the enclosing differentiated step keeps only
        layer BOUNDARY activations in HBM and recomputes the interiors in
        backward — the standard long-sequence memory schedule (task brief:
        'jax.checkpoint to trade FLOPs for memory')."""
        for cell in self.transformer_cells._children.values():
            cell.hybridize(active, remat=active)


class BERTModel(HybridBlock):
    """Embeddings + encoder + pooler + MLM decoder + NSP classifier.
    Reference: gluonnlp BERTModel.

    forward(inputs, token_types, valid_length=None, masked_positions=None)
      -> (sequence_output, pooled_output[, mlm_scores][, nsp_scores])
    """

    def __init__(self, encoder, vocab_size, token_type_vocab_size=2,
                 units=768, embed_dropout=0.1, use_pooler=True,
                 use_decoder=True, use_classifier=True, **kwargs):
        super().__init__(**kwargs)
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self._use_classifier = use_classifier
        self._vocab_size = vocab_size
        with self.name_scope():
            self.encoder = encoder
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(token_type_vocab_size,
                                                 units,
                                                 prefix="token_type_embed_")
            self.embed_dropout = nn.Dropout(embed_dropout)
            if use_pooler:
                self.pooler = nn.Dense(units, activation="tanh",
                                       flatten=False, prefix="pooler_")
            if use_decoder:
                # MLM head; output projection tied to word_embed in
                # hybrid_forward (weight tying, reference decoder._collect)
                self.decoder_transform = nn.Dense(units, flatten=False,
                                                  activation=None,
                                                  prefix="decoder_transform_")
                self.decoder_norm = nn.LayerNorm(epsilon=1e-12)
                self.decoder_bias = self.params.get(
                    "decoder_bias", shape=(vocab_size,), init="zeros")
            if use_classifier:
                self.classifier = nn.Dense(2, flatten=False,
                                           prefix="classifier_")

    def _attention_mask(self, F, inputs, valid_length):
        if valid_length is None:
            return None
        seq_len = inputs.shape[1]
        steps = F.arange(seq_len).reshape((1, 1, seq_len))
        mask = steps < F.reshape(valid_length, (-1, 1, 1))  # (B,1,Lk)
        return F.broadcast_to(mask.astype("float32"),
                              (inputs.shape[0], seq_len, seq_len))

    def hybrid_forward(self, F, inputs, token_types, valid_length=None,
                       masked_positions=None, position_weight=None,
                       decoder_bias=None):
        x = self.word_embed(inputs) + self.token_type_embed(token_types)
        x = self.embed_dropout(x)
        mask = self._attention_mask(F, inputs, valid_length)
        seq_out = self.encoder(x, mask)
        outputs = [seq_out]
        pooled = None
        if self._use_pooler:
            cls = F.slice_axis(seq_out, axis=1, begin=0, end=1)
            pooled = self.pooler(F.reshape(cls, (inputs.shape[0], -1)))
            outputs.append(pooled)
        if self._use_decoder and masked_positions is not None:
            picked = F.gather_positions(seq_out, masked_positions)
            h = self.decoder_norm(
                F.LeakyReLU(self.decoder_transform(picked), act_type="gelu"))
            emb = self.word_embed.weight.data()
            scores = F.dot(h, emb, transpose_b=True) + decoder_bias
            outputs.append(scores)
        if self._use_classifier and pooled is not None:
            outputs.append(self.classifier(pooled))
        return outputs[0] if len(outputs) == 1 else tuple(outputs)


def get_bert_model(num_layers=12, units=768, hidden_size=3072, num_heads=12,
                   vocab_size=30522, max_length=512, dropout=0.1,
                   use_flash=False, **kwargs):
    encoder = BERTEncoder(num_layers=num_layers, units=units,
                          hidden_size=hidden_size, num_heads=num_heads,
                          dropout=dropout, max_length=max_length,
                          use_flash=use_flash, prefix="encoder_")
    return BERTModel(encoder, vocab_size, units=units, embed_dropout=dropout,
                     **kwargs)


def bert_12_768_12(vocab_size=30522, **kwargs):
    """BERT-base. Reference: gluonnlp bert_12_768_12."""
    return get_bert_model(12, 768, 3072, 12, vocab_size=vocab_size, **kwargs)


def bert_24_1024_16(vocab_size=30522, **kwargs):
    """BERT-large. Reference: gluonnlp bert_24_1024_16."""
    return get_bert_model(24, 1024, 4096, 16, vocab_size=vocab_size, **kwargs)
