"""Local model store for pretrained zoo weights.

Reference: python/mxnet/gluon/model_zoo/model_store.py — get_model_file
resolved ``<name>-<sha1-prefix>.params`` in a local root and downloaded
from the model zoo bucket on miss. This environment has zero egress, so
the store is strictly local: drop reference-era ``.params`` files (the
NDARRAY_V2 reader in ndarray/utils.py parses them byte-for-byte) or
files saved by this framework into the root and ``get_model(name,
pretrained=True)`` picks them up.

Root resolution order: explicit ``root=`` argument, ``MXTPU_MODEL_STORE``
env var, ``~/.mxnet/models`` (the reference default, so an existing
reference model cache is found as-is).
"""
from __future__ import annotations

import glob
import os

from ...base import MXNetError

__all__ = ["get_model_file", "default_root"]


def default_root():
    return os.environ.get("MXTPU_MODEL_STORE",
                          os.path.join("~", ".mxnet", "models"))


def get_model_file(name, root=None):
    """Resolve the ``.params`` file for zoo model ``name``.

    Accepts ``<name>.params`` or the reference's hashed
    ``<name>-<hash>.params`` (newest wins when several match). Reference
    cache files spell width multipliers with dots (``squeezenet1.0``),
    registry names with underscores — both are tried."""
    import re
    root = os.path.expanduser(root or default_root())
    dotted = re.sub(r"(?<=\d)_(?=\d)", ".", name)
    for cand in dict.fromkeys((name, dotted)):
        exact = os.path.join(root, f"{cand}.params")
        if os.path.isfile(exact):
            return exact
        hashed = sorted(glob.glob(os.path.join(root, f"{cand}-*.params")),
                        key=os.path.getmtime)
        if hashed:
            return hashed[-1]
    raise MXNetError(
        f"No pretrained weights for '{name}' in model store '{root}' "
        f"(looked for {name}.params and {name}-*.params). This build has "
        "no network access: place a reference-era .params file (read "
        "natively) or one saved by save_parameters() there, or pass "
        "root=/MXTPU_MODEL_STORE.")
