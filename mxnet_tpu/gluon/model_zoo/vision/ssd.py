"""SSD detector (GluonCV parity: gluoncv/model_zoo/ssd/ssd.py).

TPU-first design: all shapes static — anchors are generated at first forward
from the (static) feature-map sizes and cached as constants; train mode
returns raw (cls_preds, box_preds, anchors) for MultiBoxTarget; eval mode
decodes + NMS in-graph (mx.nd.contrib.box_nms is a fixed-trip fori_loop).
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn
from .resnet import get_resnet

__all__ = ["SSD", "ssd_300_resnet34_v1", "ssd_512_resnet50_v1",
           "get_ssd"]


class ConvPredictor(HybridBlock):
    """3x3 conv predictor head (gluoncv ConvPredictor)."""

    def __init__(self, num_channel, **kwargs):
        super().__init__(**kwargs)
        self.predictor = nn.Conv2D(num_channel, 3, 1, 1)

    def hybrid_forward(self, F, x):
        return self.predictor(x)


class SSDAnchorGenerator(HybridBlock):
    """Per-scale anchors via MultiBoxPrior (multibox_prior.cc semantics)."""

    def __init__(self, sizes, ratios, step, **kwargs):
        super().__init__(**kwargs)
        self._sizes = sizes
        self._ratios = ratios
        self._step = step

    @property
    def num_depth(self):
        return len(self._sizes) + len(self._ratios) - 1

    def hybrid_forward(self, F, x):
        from ....ndarray import contrib
        return contrib.MultiBoxPrior(
            x, sizes=self._sizes, ratios=self._ratios, clip=False,
            steps=(self._step, self._step))


class SSD(HybridBlock):
    """Single Shot Detector.

    features: HybridBlock returning a list of multi-scale feature maps;
    sizes/ratios: per-scale anchor specs (len == num scales).
    """

    def __init__(self, features, sizes, ratios, steps, classes,
                 use_bn=True, nms_thresh=0.45, nms_topk=400, post_nms=100,
                 anchor_alloc_size=128, **kwargs):
        super().__init__(**kwargs)
        if len(sizes) != len(ratios) or len(sizes) != len(steps):
            raise MXNetError("sizes/ratios/steps length mismatch")
        self.classes = list(classes)
        self.num_classes = len(self.classes)
        self.nms_thresh = nms_thresh
        self.nms_topk = nms_topk
        self.post_nms = post_nms
        self.features = features
        self.class_predictors = nn.HybridSequential()
        self.box_predictors = nn.HybridSequential()
        self.anchor_generators = nn.HybridSequential()
        for s, r, st in zip(sizes, ratios, steps):
            gen = SSDAnchorGenerator(s, r, st)
            self.anchor_generators.add(gen)
            na = gen.num_depth
            self.class_predictors.add(
                ConvPredictor(na * (self.num_classes + 1)))
            self.box_predictors.add(ConvPredictor(na * 4))

    def set_nms(self, nms_thresh=0.45, nms_topk=400, post_nms=100):
        self.nms_thresh = nms_thresh
        self.nms_topk = nms_topk
        self.post_nms = post_nms

    def hybrid_forward(self, F, x):
        from ....ndarray import contrib
        from .... import _tape
        feats = self.features(x)
        cls_preds, box_preds, anchors = [], [], []
        for feat, cp, bp, ag in zip(feats, self.class_predictors,
                                    self.box_predictors,
                                    self.anchor_generators):
            c = cp(feat)    # (B, na*(C+1), H, W)
            b = bp(feat)
            # NCHW -> (B, HW*na, C+1)
            c = F.reshape(F.transpose(c, (0, 2, 3, 1)),
                          (c.shape[0], -1, self.num_classes + 1))
            b = F.reshape(F.transpose(b, (0, 2, 3, 1)), (b.shape[0], -1, 4))
            cls_preds.append(c)
            box_preds.append(b)
            anchors.append(ag(feat))
        cls_pred = F.concat(*cls_preds, dim=1)
        box_pred = F.concat(*box_preds, dim=1)
        anchor = F.concat(*anchors, dim=1)
        if _tape.is_training():
            return cls_pred, box_pred, anchor
        # inference: decode + nms -> (ids, scores, bboxes)
        cls_prob = F.softmax(cls_pred, axis=-1)
        cls_prob_t = F.transpose(cls_prob, (0, 2, 1))  # (B, C+1, N)
        dets = contrib.MultiBoxDetection(
            cls_prob_t, F.reshape(box_pred, (box_pred.shape[0], -1)),
            anchor, nms_threshold=self.nms_thresh, nms_topk=self.nms_topk)
        ids = F.slice_axis(dets, axis=2, begin=0, end=1)
        scores = F.slice_axis(dets, axis=2, begin=1, end=2)
        bboxes = F.slice_axis(dets, axis=2, begin=2, end=6)
        return ids, scores, bboxes


class _ResNetFeatures(HybridBlock):
    """ResNet truncated features + extra downsample stages (gluoncv
    FeatureExpander equivalent, conv-based)."""

    def __init__(self, base_net, num_extras=3, extra_channels=(512, 256, 256),
                 **kwargs):
        super().__init__(**kwargs)
        feats = base_net.features
        # stages: [0: conv..pool] [resnet stages] [-2 pool, -1 flatten] vary;
        # split: everything up to last stage = stage1 out; last stage = stage2
        self.stage1 = nn.HybridSequential()
        self.stage2 = nn.HybridSequential()
        blocks = list(feats._children.values())
        # drop trailing global pool / flatten if present
        core = [b for b in blocks if b.__class__.__name__ not in
                ("GlobalAvgPool2D", "Flatten")]
        for b in core[:-1]:
            self.stage1.add(b)
        self.stage2.add(core[-1])
        self.extras = nn.HybridSequential()
        for ch in extra_channels[:num_extras]:
            ext = nn.HybridSequential()
            ext.add(nn.Conv2D(ch // 2, 1, 1, 0, use_bias=False))
            ext.add(nn.BatchNorm())
            ext.add(nn.Activation("relu"))
            ext.add(nn.Conv2D(ch, 3, 2, 1, use_bias=False))
            ext.add(nn.BatchNorm())
            ext.add(nn.Activation("relu"))
            self.extras.add(ext)

    def hybrid_forward(self, F, x):
        outs = []
        x = self.stage1(x)
        outs.append(x)
        x = self.stage2(x)
        outs.append(x)
        for ext in self.extras:
            x = ext(x)
            outs.append(x)
        return outs


_VOC_CLASSES = tuple(f"class_{i}" for i in range(20))


def get_ssd(base_name, base_size, sizes, ratios, steps, classes=_VOC_CLASSES,
            **kwargs):
    if base_name.startswith("resnet"):
        ver = 1
        layers = {"resnet34": 34, "resnet50": 50}[base_name]
        base = get_resnet(ver, layers)
    else:
        raise MXNetError(f"unsupported SSD base {base_name}")
    features = _ResNetFeatures(base)
    return SSD(features, sizes, ratios, steps, classes, **kwargs)


def ssd_300_resnet34_v1(classes=_VOC_CLASSES, **kwargs):
    """SSD 300 with ResNet-34 (gluoncv ssd_300_resnet34_v1b parity)."""
    return get_ssd(
        "resnet34", 300,
        sizes=[[0.1, 0.141], [0.2, 0.272], [0.37, 0.447], [0.54, 0.619],
               [0.71, 0.79]],
        ratios=[[1, 2, 0.5]] * 3 + [[1, 2, 0.5]] * 2,
        steps=[-1.0] * 5, classes=classes, **kwargs)


def ssd_512_resnet50_v1(classes=_VOC_CLASSES, **kwargs):
    """SSD 512 with ResNet-50 (gluoncv ssd_512_resnet50_v1 parity)."""
    return get_ssd(
        "resnet50", 512,
        sizes=[[0.1, 0.141], [0.2, 0.272], [0.37, 0.447], [0.54, 0.619],
               [0.71, 0.79]],
        ratios=[[1, 2, 0.5]] * 5,
        steps=[-1.0] * 5, classes=classes, **kwargs)
