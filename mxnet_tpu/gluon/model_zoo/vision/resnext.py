"""ResNeXt and SE-ResNet for the vision zoo (GluonCV parity:
gluoncv/model_zoo/resnext.py, senet.py).

ResNeXt ("Aggregated Residual Transformations", Xie et al. 2017): the
bottleneck's 3x3 becomes a cardinality-grouped conv — one
`lax.conv_general_dilated(feature_group_count=C)` per block, which XLA:TPU
tiles as a single batched MXU contraction (the reference needed cuDNN grouped
kernels). SE-ResNet adds squeeze-excitation channel gating (Hu et al. 2018) —
a global pool + two 1x1 convs + sigmoid scale that XLA fuses into the
residual epilogue.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["ResNeXtBlock", "ResNeXt", "resnext50_32x4d", "resnext101_32x4d",
           "resnext101_64x4d", "se_resnet50", "se_resnet101",
           "SEBlock"]


class SEBlock(HybridBlock):
    """Squeeze-excitation gate: x * sigmoid(W2 relu(W1 gap(x)))."""

    def __init__(self, channels, reduction=16, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.fc1 = nn.Conv2D(max(channels // reduction, 4), 1)
            self.fc2 = nn.Conv2D(channels, 1)

    def hybrid_forward(self, F, x):
        w = F.mean(x, axis=(2, 3), keepdims=True)
        w = F.sigmoid(self.fc2(F.relu(self.fc1(w))))
        return x * w


class ResNeXtBlock(HybridBlock):
    """Grouped bottleneck, optionally with an SE gate (gluoncv resnext.py
    Block)."""

    def __init__(self, channels, cardinality, bottleneck_width, stride,
                 downsample=False, use_se=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        D = int(channels * bottleneck_width / 64.0)
        group_width = cardinality * D
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            self.body.add(nn.Conv2D(group_width, 1, use_bias=False))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))
            self.body.add(nn.Conv2D(group_width, 3, stride, 1,
                                    groups=cardinality, use_bias=False))
            self.body.add(nn.BatchNorm())
            self.body.add(nn.Activation("relu"))
            self.body.add(nn.Conv2D(channels * 4, 1, use_bias=False))
            self.body.add(nn.BatchNorm())
            self.se = SEBlock(channels * 4) if use_se else None
            if downsample:
                self.downsample = nn.HybridSequential(prefix="")
                self.downsample.add(nn.Conv2D(channels * 4, 1, stride,
                                              use_bias=False))
                self.downsample.add(nn.BatchNorm())
            else:
                self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        out = self.body(x)
        if self.se is not None:
            out = self.se(out)
        if self.downsample is not None:
            residual = self.downsample(x)
        return F.Activation(out + residual, act_type="relu")


class ResNeXt(HybridBlock):
    def __init__(self, layers, cardinality=32, bottleneck_width=4,
                 classes=1000, use_se=False, **kwargs):
        super().__init__(**kwargs)
        channels = 64
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(64, 7, 2, 3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                layer = nn.HybridSequential(prefix=f"stage{i + 1}_")
                with layer.name_scope():
                    layer.add(ResNeXtBlock(
                        channels, cardinality, bottleneck_width, stride,
                        downsample=True, use_se=use_se, prefix=""))
                    for _ in range(num_layer - 1):
                        layer.add(ResNeXtBlock(
                            channels, cardinality, bottleneck_width, 1,
                            use_se=use_se, prefix=""))
                self.features.add(layer)
                channels *= 2
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(F.flatten(x))


def _resnext(layers, cardinality, bottleneck_width, use_se=False,
             pretrained=False, **kwargs):
    if pretrained:
        raise MXNetError("pretrained weights unavailable offline; use "
                         "load_parameters with a local .params file")
    return ResNeXt(layers, cardinality, bottleneck_width, use_se=use_se,
                   **kwargs)


def resnext50_32x4d(**kwargs):
    return _resnext([3, 4, 6, 3], 32, 4, **kwargs)


def resnext101_32x4d(**kwargs):
    return _resnext([3, 4, 23, 3], 32, 4, **kwargs)


def resnext101_64x4d(**kwargs):
    return _resnext([3, 4, 23, 3], 64, 4, **kwargs)


def se_resnet50(**kwargs):
    # gluoncv se_resnet: cardinality 1, width 64 == plain bottleneck + SE
    return _resnext([3, 4, 6, 3], 1, 64, use_se=True, **kwargs)


def se_resnet101(**kwargs):
    return _resnext([3, 4, 23, 3], 1, 64, use_se=True, **kwargs)
