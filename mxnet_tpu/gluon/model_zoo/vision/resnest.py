"""ResNeSt: Split-Attention Networks — the reference fork author's model
family (GluonCV `gluoncv/model_zoo/resnest.py`, `splat.py`; the fork
zhanghang1989/incubator-mxnet exists to support it).

TPU-native implementation: the split-attention block is expressed as one
grouped conv + reshapes + a radix-softmax — all static shapes, so XLA fuses
the attention arithmetic into the surrounding convs. Structure (deep stem,
avg-down downsampling, avd pooling in the bottleneck) follows the paper
"ResNeSt: Split-Attention Networks" (Zhang et al., 2020).
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["SplitAttentionConv", "ResNeStBlock", "ResNeSt",
           "resnest50", "resnest101", "resnest200", "resnest269"]


class SplitAttentionConv(HybridBlock):
    """Split-attention grouped conv (GluonCV splat.py SplitAttentionConv).

    radix feature groups are produced by one grouped conv; a squeezed
    gate (global pool -> fc1 -> fc2 -> softmax over radix) reweights and
    sums them. radix=1 degenerates to SE-style sigmoid gating.
    """

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, radix=2, reduction_factor=4,
                 norm_layer=nn.BatchNorm, **kwargs):
        super().__init__(**kwargs)
        self._radix = radix
        self._cardinality = groups
        self._channels = channels
        inter_channels = max(channels * radix // reduction_factor, 32)
        with self.name_scope():
            self.conv = nn.Conv2D(channels * radix, kernel_size, strides,
                                  padding, dilation, groups=groups * radix,
                                  use_bias=False)
            self.bn = norm_layer()
            self.relu = nn.Activation("relu")
            self.fc1 = nn.Conv2D(inter_channels, 1, groups=groups)
            self.bn1 = norm_layer()
            self.fc2 = nn.Conv2D(channels * radix, 1, groups=groups)

    def hybrid_forward(self, F, x):
        r, ch = self._radix, self._channels
        x = self.relu(self.bn(self.conv(x)))            # (B, r*ch, H, W)
        if r > 1:
            splits = F.reshape(x, (0, -4, r, ch, -2))   # (B, r, ch, H, W)
            gap = F.sum(splits, axis=1)                 # (B, ch, H, W)
        else:
            gap = x
        gap = F.mean(gap, axis=(2, 3), keepdims=True)   # (B, ch, 1, 1)
        gate = self.fc2(self.relu(self.bn1(self.fc1(gap))))  # (B, r*ch, 1, 1)
        if r > 1:
            # softmax over the radix axis, per cardinal group
            g = self._cardinality
            gate = F.reshape(gate, (0, g, r, ch // g))
            gate = F.softmax(gate, axis=2)
            gate = F.reshape(F.transpose(gate, axes=(0, 2, 1, 3)),
                             (0, r, ch, 1, 1))          # (B, r, ch, 1, 1)
            return F.sum(splits * gate, axis=1)
        gate = F.sigmoid(gate)
        return x * gate


class ResNeStBlock(HybridBlock):
    """ResNeSt bottleneck: 1x1 -> SplAt 3x3 (with avd pooling on stride-2
    blocks) -> 1x1, avg-down residual."""

    expansion = 4

    def __init__(self, planes, strides=1, dilation=1, downsample=None,
                 radix=2, cardinality=1, bottleneck_width=64, avd=True,
                 avd_first=False, norm_layer=nn.BatchNorm, **kwargs):
        super().__init__(**kwargs)
        group_width = int(planes * (bottleneck_width / 64.0)) * cardinality
        self._avd = avd and strides > 1
        self._avd_first = avd_first
        with self.name_scope():
            self.conv1 = nn.Conv2D(group_width, 1, use_bias=False)
            self.bn1 = norm_layer()
            self.relu = nn.Activation("relu")
            if self._avd:
                self.avd_layer = nn.AvgPool2D(3, strides, padding=1)
                strides = 1
            self.conv2 = SplitAttentionConv(
                group_width, 3, strides, padding=dilation, dilation=dilation,
                groups=cardinality, radix=radix, norm_layer=norm_layer)
            self.conv3 = nn.Conv2D(planes * 4, 1, use_bias=False)
            self.bn3 = norm_layer()
            self.downsample = downsample

    def hybrid_forward(self, F, x):
        residual = x
        out = self.relu(self.bn1(self.conv1(x)))
        if self._avd and self._avd_first:
            out = self.avd_layer(out)
        out = self.conv2(out)
        if self._avd and not self._avd_first:
            out = self.avd_layer(out)
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu(out + residual)


class ResNeSt(HybridBlock):
    """ResNeSt-d trunk: deep 3x3x3 stem, avg-down shortcuts, split-attention
    bottlenecks (GluonCV resnest.py)."""

    def __init__(self, layers, classes=1000, radix=2, cardinality=1,
                 bottleneck_width=64, stem_width=32, norm_layer=nn.BatchNorm,
                 **kwargs):
        super().__init__(**kwargs)
        self._block_args = dict(radix=radix, cardinality=cardinality,
                                bottleneck_width=bottleneck_width,
                                norm_layer=norm_layer)
        with self.name_scope():
            self.stem = nn.HybridSequential(prefix="stem_")
            for channels, s in ((stem_width, 2), (stem_width, 1),
                                (stem_width * 2, 1)):
                self.stem.add(nn.Conv2D(channels, 3, s, 1, use_bias=False))
                self.stem.add(norm_layer())
                self.stem.add(nn.Activation("relu"))
            self.maxpool = nn.MaxPool2D(3, 2, 1)
            planes = (64, 128, 256, 512)
            self.layer1 = self._make_layer(planes[0], layers[0], 1,
                                           norm_layer)
            self.layer2 = self._make_layer(planes[1], layers[1], 2,
                                           norm_layer)
            self.layer3 = self._make_layer(planes[2], layers[2], 2,
                                           norm_layer)
            self.layer4 = self._make_layer(planes[3], layers[3], 2,
                                           norm_layer)
            self.avgpool = nn.GlobalAvgPool2D()
            self.fc = nn.Dense(classes)

    def _make_layer(self, planes, blocks, strides, norm_layer):
        layer = nn.HybridSequential()
        downsample = nn.HybridSequential()
        if strides != 1:
            # avg_down: pool does the striding, 1x1 conv keeps stride 1
            downsample.add(nn.AvgPool2D(strides, strides,
                                        count_include_pad=False))
        downsample.add(nn.Conv2D(planes * 4, 1, use_bias=False))
        downsample.add(norm_layer())
        layer.add(ResNeStBlock(planes, strides, downsample=downsample,
                               **self._block_args))
        for _ in range(1, blocks):
            layer.add(ResNeStBlock(planes, 1, **self._block_args))
        return layer

    def hybrid_forward(self, F, x):
        x = self.maxpool(self.stem(x))
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        x = self.avgpool(x)
        return self.fc(F.flatten(x))


def _resnest(layers, stem_width, pretrained=False, **kwargs):
    if pretrained:
        raise MXNetError("pretrained weights unavailable offline; use "
                         "load_parameters with a local .params file")
    return ResNeSt(layers, stem_width=stem_width, **kwargs)


def resnest50(**kwargs):
    return _resnest([3, 4, 6, 3], 32, **kwargs)


def resnest101(**kwargs):
    return _resnest([3, 4, 23, 3], 64, **kwargs)


def resnest200(**kwargs):
    return _resnest([3, 24, 36, 3], 64, **kwargs)


def resnest269(**kwargs):
    return _resnest([3, 30, 48, 8], 64, **kwargs)
