"""Pose estimation zoo: SimplePose (GluonCV parity:
gluoncv/model_zoo/simple_pose/simple_pose_resnet.py).

"Simple Baselines for Human Pose Estimation" (Xiao et al., 2018): a ResNet
trunk followed by three 4x4/stride-2 deconvolution stages and a 1x1 head
producing per-joint heatmaps. Deconvs lower to lax.conv_transpose (one MXU
matmul per stage after XLA tiling); heatmap argmax decoding is a pure
jnp reduction, no host round-trip.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn
from . import segmentation as _v1b

__all__ = ["SimplePoseResNet", "simple_pose_resnet18_v1b",
           "simple_pose_resnet50_v1b", "heatmap_to_coord"]

_TRUNKS = {"resnet18_v1b": _v1b.resnet18_v1b,
           "resnet34_v1b": _v1b.resnet34_v1b,
           "resnet50_v1b": _v1b.resnet50_v1b,
           "resnet101_v1b": _v1b.resnet101_v1b}


class SimplePoseResNet(HybridBlock):
    def __init__(self, base_name="resnet50_v1b", num_joints=17,
                 num_deconv_layers=3, num_deconv_filters=256,
                 pretrained_base=False, **kwargs):
        super().__init__(**kwargs)
        if base_name not in _TRUNKS:
            raise MXNetError(f"unknown pose trunk {base_name!r}; "
                             f"options: {sorted(_TRUNKS)}")
        # true v1b trunk (stride on the 3x3 conv, BasicBlockV1b for 18/34)
        # at output stride 32 — gluoncv simple_pose_resnet.py
        trunk = _TRUNKS[base_name](classes=1, dilated=False)
        with self.name_scope():
            # everything before global pool: stem + 4 stages
            self.features = nn.HybridSequential(prefix="features_")
            for name in ("conv1", "bn1", "relu", "maxpool",
                         "layer1", "layer2", "layer3", "layer4"):
                self.features.add(getattr(trunk, name))
            self.deconv_layers = nn.HybridSequential(prefix="deconv_")
            for _ in range(num_deconv_layers):
                self.deconv_layers.add(nn.Conv2DTranspose(
                    num_deconv_filters, kernel_size=4, strides=2, padding=1,
                    use_bias=False))
                self.deconv_layers.add(nn.BatchNorm())
                self.deconv_layers.add(nn.Activation("relu"))
            self.final_layer = nn.Conv2D(num_joints, kernel_size=1)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.deconv_layers(x)
        return self.final_layer(x)


def heatmap_to_coord(heatmaps):
    """Decode (B, K, H, W) heatmaps to ((B, K, 2) coords, (B, K) scores) —
    gluoncv.utils.metrics (get_max_pred) semantics, computed on device."""
    import jax.numpy as jnp
    from ....ndarray import NDArray, from_jax
    hm = heatmaps.data if isinstance(heatmaps, NDArray) else heatmaps
    b, k, h, w = hm.shape
    flat = hm.reshape(b, k, h * w)
    idx = jnp.argmax(flat, axis=-1)
    scores = jnp.max(flat, axis=-1)
    coords = jnp.stack([idx % w, idx // w], axis=-1).astype(jnp.float32)
    return from_jax(coords), from_jax(scores)


def simple_pose_resnet18_v1b(**kwargs):
    return SimplePoseResNet("resnet18_v1b", **kwargs)


def simple_pose_resnet50_v1b(**kwargs):
    return SimplePoseResNet("resnet50_v1b", **kwargs)
