"""ResNet V1/V2 for the Gluon model zoo.

Reference: python/mxnet/gluon/model_zoo/vision/resnet.py (resnet18-152 v1/v2,
BasicBlock/BottleneckBlock, thumbnail mode for CIFAR). Weight layout and
block structure match the reference so `.params` checkpoints load.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1", "ResNetV2", "SpaceToDepthStem",
           "BasicBlockV1", "BasicBlockV2",
           "BottleneckV1", "BottleneckV2", "resnet18_v1", "resnet34_v1",
           "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
           "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "get_resnet"]


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class SpaceToDepthStem(HybridBlock):
    """Numerically exact space-to-depth rewrite of the 7x7/stride-2 ImageNet
    stem (the MLPerf ResNet trick).

    The stride-2 7x7 conv over (B,3,224,224) becomes a stride-1 4x4 conv over
    the space-to-depth(2) input (B,12,112,112): identical FLOPs and output,
    but 4x more input channels feeding the MXU's contracted dimension and 4x
    fewer spatial positions — the stem stops being the worst-tiled conv in the
    net. The parameter keeps the reference shape (C,3,7,7)
    (python/mxnet/gluon/model_zoo/vision/resnet.py stem conv), and the 4x4/12ch
    kernel is re-tiled from it in-graph each step (a few kB; XLA hoists it).

    Derivation: out(i,j) = sum_{ky,kx,c} x[c, 2i+ky-3, 2j+kx-3] w[o,c,ky,kx].
    Writing ky = 2m+dy-1 (m in 0..3, dy in 0..1) turns the sum into a 4-tap
    stride-1 conv over the s2d grid with symmetric pad 2, valid outputs 0..111.
    """

    def __init__(self, channels, in_channels=3, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(channels, in_channels, 7, 7),
                allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        o, c_in = self._channels, self._in_channels
        try:
            if int(x.shape[1]) != c_in:
                raise MXNetError(
                    f"SpaceToDepthStem built for in_channels={c_in} but got "
                    f"input with {int(x.shape[1])} channels; pass "
                    f"in_channels= to the stem (reference stock stem defers "
                    f"in_channels).")
        except (TypeError, IndexError):
            pass   # shapeless symbolic trace
        try:
            oh, ow = int(x.shape[2]) % 2, int(x.shape[3]) % 2
        except (TypeError, IndexError):   # shapeless symbolic trace
            oh = ow = 0
        if oh or ow:
            # odd spatial size: the 7x7/p3 conv reads zeros past the edge
            # anyway, so one explicit zero row/col keeps exact equivalence
            x = F.Pad(x, mode="constant",
                      pad_width=(0, 0, 0, 0, 0, oh, 0, ow))
        xs = F.space_to_depth(x, 2)
        # (O,C,7,7) -> pad front of each spatial dim -> (O,C,8,8); index
        # kyp = ky+1 = 2m+dy splits as (m, dy)
        w = F.Pad(weight, mode="constant",
                  pad_width=(0, 0, 0, 0, 1, 0, 1, 0))
        w = F.reshape(w, (o, c_in, 4, 2, 4, 2))        # (O, c, m, dy, n, dx)
        w = F.transpose(w, axes=(0, 3, 5, 1, 2, 4))    # (O, dy, dx, c, m, n)
        w = F.reshape(w, (o, 4 * c_in, 4, 4))          # ch = (dy*2+dx)*C + c
        y = F.Convolution(xs, w, None, kernel=(4, 4), stride=(1, 1),
                          pad=(2, 2), num_filter=o, no_bias=True)
        return F.slice(y, begin=(None, None, 0, 0),
                       end=(None, None, -1, -1))


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1,
                                          strides=stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, kernel_size=1, strides=1,
                               use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, kernel_size=1, strides=1,
                               use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 s2d_stem=False, stem_in_channels=3, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                # prefix keeps the param named conv0_weight so checkpoints
                # interop between s2d_stem=True and the stock stem
                self.features.add(SpaceToDepthStem(channels[0],
                                                   stem_in_channels,
                                                   prefix="conv0_")
                                  if s2d_stem
                                  else nn.Conv2D(channels[0], 7, 2, 3,
                                                 use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i]))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 s2d_stem=False, stem_in_channels=3, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(SpaceToDepthStem(channels[0],
                                                   stem_in_channels,
                                                   prefix="conv0_")
                                  if s2d_stem
                                  else nn.Conv2D(channels[0], 7, 2, 3,
                                                 use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = self.output(x)
        return x


resnet_spec = {18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
               34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
               50: ("bottle_neck", [3, 4, 6, 3],
                    [64, 256, 512, 1024, 2048]),
               101: ("bottle_neck", [3, 4, 23, 3],
                     [64, 256, 512, 1024, 2048]),
               152: ("bottle_neck", [3, 8, 36, 3],
                     [64, 256, 512, 1024, 2048])}

resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [{"basic_block": BasicBlockV1,
                          "bottle_neck": BottleneckV1},
                         {"basic_block": BasicBlockV2,
                          "bottle_neck": BottleneckV2}]


def get_resnet(version, num_layers, pretrained=False, ctx=None, root=None,
               **kwargs):
    assert num_layers in resnet_spec, \
        f"Invalid resnet depth {num_layers}; options: {sorted(resnet_spec)}"
    assert 1 <= version <= 2
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        raise MXNetError("pretrained weights unavailable offline; use "
                         "load_parameters with a local .params file")
    return net


def resnet18_v1(**kwargs):
    return get_resnet(1, 18, **kwargs)


def resnet34_v1(**kwargs):
    return get_resnet(1, 34, **kwargs)


def resnet50_v1(**kwargs):
    return get_resnet(1, 50, **kwargs)


def resnet101_v1(**kwargs):
    return get_resnet(1, 101, **kwargs)


def resnet152_v1(**kwargs):
    return get_resnet(1, 152, **kwargs)


def resnet18_v2(**kwargs):
    return get_resnet(2, 18, **kwargs)


def resnet34_v2(**kwargs):
    return get_resnet(2, 34, **kwargs)


def resnet50_v2(**kwargs):
    return get_resnet(2, 50, **kwargs)


def resnet101_v2(**kwargs):
    return get_resnet(2, 101, **kwargs)


def resnet152_v2(**kwargs):
    return get_resnet(2, 152, **kwargs)
