"""``gluon.model_zoo.vision`` — in-repo vision models.

Reference: python/mxnet/gluon/model_zoo/vision/ (alexnet, densenet,
inception, resnet v1/v2, squeezenet, vgg, mobilenet v1/v2) — SURVEY.md §2.2.
"""
from .resnet import *  # noqa: F401,F403
from .alexnet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .inception import *  # noqa: F401,F403
from .ssd import *  # noqa: F401,F403
from .yolo import *  # noqa: F401,F403
from .segmentation import *  # noqa: F401,F403
from .rcnn import *  # noqa: F401,F403
from .resnest import *  # noqa: F401,F403
from .pose import *  # noqa: F401,F403
from .resnext import *  # noqa: F401,F403

from ....base import MXNetError


_MODELS = {}


def _register_models():
    import importlib
    mods = [importlib.import_module(f"{__name__}.{m}")
            for m in ("resnet", "alexnet", "vgg", "squeezenet", "mobilenet",
                      "densenet", "inception", "ssd", "yolo", "segmentation",
                      "rcnn", "resnest", "pose", "resnext")]
    non_models = {"heatmap_to_coord"}   # exported utilities, not factories
    for mod in mods:
        for name in mod.__all__:
            fn = getattr(mod, name)
            if callable(fn) and name[0].islower() and \
                    not name.startswith("get_") and name not in non_models:
                _MODELS[name] = fn


_register_models()


def get_model(name, **kwargs):
    """Reference: model_zoo.vision.get_model(name)."""
    name = name.lower().replace("-", "_")
    if name not in _MODELS:
        raise MXNetError(
            f"Model {name} is not supported. Available: {sorted(_MODELS)}")
    return _MODELS[name](**kwargs)
