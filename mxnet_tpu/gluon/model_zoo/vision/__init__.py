"""``gluon.model_zoo.vision`` — in-repo vision models.

Reference: python/mxnet/gluon/model_zoo/vision/ (alexnet, densenet,
inception, resnet v1/v2, squeezenet, vgg, mobilenet v1/v2) — SURVEY.md §2.2.
"""
from .resnet import *  # noqa: F401,F403
from .alexnet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .inception import *  # noqa: F401,F403
from .ssd import *  # noqa: F401,F403
from .yolo import *  # noqa: F401,F403
from .segmentation import *  # noqa: F401,F403
from .rcnn import *  # noqa: F401,F403
from .resnest import *  # noqa: F401,F403
from .pose import *  # noqa: F401,F403
from .resnext import *  # noqa: F401,F403

from ....base import MXNetError


_MODELS = {}


def _register_models():
    import importlib
    mods = [importlib.import_module(f"{__name__}.{m}")
            for m in ("resnet", "alexnet", "vgg", "squeezenet", "mobilenet",
                      "densenet", "inception", "ssd", "yolo", "segmentation",
                      "rcnn", "resnest", "pose", "resnext")]
    non_models = {"heatmap_to_coord"}   # exported utilities, not factories
    for mod in mods:
        for name in mod.__all__:
            fn = getattr(mod, name)
            if callable(fn) and name[0].islower() and \
                    not name.startswith("get_") and name not in non_models:
                _MODELS[name] = fn


_register_models()


def get_model(name, pretrained=False, root=None, ctx=None, **kwargs):
    """Reference: model_zoo.vision.get_model(name, pretrained=, root=).

    ``pretrained=True`` loads weights from the LOCAL model store (see
    model_store.get_model_file — reference-era NDARRAY_V2 ``.params``
    files load byte-for-byte; no download in this zero-egress build).
    ``ctx`` is accepted for API compatibility (one device context here)."""
    # reference zoo names use dots in width multipliers (squeezenet1.0,
    # mobilenet0.25); the registry keys are identifier-safe
    name = name.lower().replace("-", "_").replace(".", "_")
    if name not in _MODELS:
        raise MXNetError(
            f"Model {name} is not supported. Available: {sorted(_MODELS)}")
    net = _MODELS[name](**kwargs)
    if pretrained:
        from ..model_store import get_model_file
        net.load_parameters(get_model_file(name, root), ctx=ctx)
    return net
