"""YOLOv3 detector + Darknet-53 backbone (GluonCV parity:
gluoncv/model_zoo/yolo/{darknet.py,yolo3.py}).

TPU-first: per-scale decode is fully vectorised (grid offsets are static
constants baked at trace time); training mode returns raw per-scale
predictions; eval decodes all scales, concatenates, and runs the fixed-trip
box_nms.
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["DarknetV3", "darknet53", "YOLOV3", "yolo3_darknet53"]


def _conv2d(channel, kernel, padding, stride):
    cell = nn.HybridSequential()
    cell.add(nn.Conv2D(channel, kernel_size=kernel, strides=stride,
                       padding=padding, use_bias=False))
    cell.add(nn.BatchNorm(epsilon=1e-5, momentum=0.9))
    cell.add(nn.LeakyReLU(0.1))
    return cell


class DarknetBasicBlockV3(HybridBlock):
    def __init__(self, channel, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv2d(channel, 1, 0, 1))
        self.body.add(_conv2d(channel * 2, 3, 1, 1))

    def hybrid_forward(self, F, x):
        return x + self.body(x)


class DarknetV3(HybridBlock):
    """Darknet-53 (gluoncv darknet.py: layers [1,2,8,8,4])."""

    def __init__(self, layers=(1, 2, 8, 8, 4),
                 channels=(64, 128, 256, 512, 1024), classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(_conv2d(32, 3, 1, 1))
        for nlayer, channel in zip(layers, channels):
            self.features.add(_conv2d(channel, 3, 1, 2))
            for _ in range(nlayer):
                self.features.add(DarknetBasicBlockV3(channel // 2))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        x = self.features(x)
        x = F.Pooling(x, global_pool=True, pool_type="avg")
        return self.output(F.flatten(x))


def darknet53(classes=1000, **kwargs):
    return DarknetV3(classes=classes, **kwargs)


class YOLODetectionBlockV3(HybridBlock):
    def __init__(self, channel, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        for _ in range(2):
            self.body.add(_conv2d(channel, 1, 0, 1))
            self.body.add(_conv2d(channel * 2, 3, 1, 1))
        self.body.add(_conv2d(channel, 1, 0, 1))
        self.tip = _conv2d(channel * 2, 3, 1, 1)

    def hybrid_forward(self, F, x):
        route = self.body(x)
        return route, self.tip(route)


class YOLOOutputV3(HybridBlock):
    """Per-scale prediction + decode (gluoncv yolo3.py YOLOOutputV3)."""

    def __init__(self, num_class, anchors, stride, **kwargs):
        super().__init__(**kwargs)
        self._classes = num_class
        self._num_pred = 1 + 4 + num_class
        self._anchors = [(float(w), float(h))
                         for w, h in zip(anchors[::2], anchors[1::2])]
        self._stride = stride
        self.prediction = nn.Conv2D(len(self._anchors) * self._num_pred,
                                    kernel_size=1, padding=0, strides=1)

    def hybrid_forward(self, F, x):
        import jax
        import jax.numpy as jnp
        from ....ndarray.ndarray import apply_nary
        pred = self.prediction(x)   # (B, na*np, H, W)
        na = len(self._anchors)
        npred = self._num_pred
        stride = self._stride
        anchors = self._anchors
        ncls = self._classes

        def decode(p):
            sig = jax.nn.sigmoid
            b, _, h, w = p.shape
            p = p.reshape(b, na, npred, h, w).transpose(0, 3, 4, 1, 2)
            gy, gx = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
            aw = jnp.asarray([a[0] for a in anchors])
            ah = jnp.asarray([a[1] for a in anchors])
            cx = (sig(p[..., 0]) + gx[..., None]) * stride
            cy = (sig(p[..., 1]) + gy[..., None]) * stride
            bw = jnp.exp(p[..., 2]) * aw
            bh = jnp.exp(p[..., 3]) * ah
            obj = sig(p[..., 4:5])
            cls = sig(p[..., 5:])
            scores = obj * cls                            # (B,H,W,na,C)
            boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                               cx + bw / 2, cy + bh / 2], axis=-1)
            return (boxes.reshape(b, -1, 4),
                    scores.reshape(b, -1, ncls))

        boxes, scores = apply_nary(decode, [pred], n_out=2,
                                   name="yolo_decode")
        return pred, boxes, scores


class _Upsample(HybridBlock):
    def __init__(self, scale=2, **kwargs):
        super().__init__(**kwargs)
        self._scale = scale

    def hybrid_forward(self, F, x):
        from ....ndarray.ndarray import apply_nary
        import jax.numpy as jnp
        s = self._scale

        def fn(d):
            return jnp.repeat(jnp.repeat(d, s, axis=2), s, axis=3)
        return apply_nary(fn, [x], name="upsample")


_COCO_ANCHORS = [[10, 13, 16, 30, 33, 23],
                 [30, 61, 62, 45, 59, 119],
                 [116, 90, 156, 198, 373, 326]]
_STRIDES = [8, 16, 32]


class YOLOV3(HybridBlock):
    """YOLOv3 (gluoncv yolo3.py).

    Training mode returns the raw per-scale conv outputs (B, na*np, H, W)
    plus decoded (boxes, scores) per scale; eval returns (ids, scores,
    bboxes) after NMS.
    """

    def __init__(self, stages, channels=(512, 256, 128), classes=80,
                 anchors=_COCO_ANCHORS, strides=_STRIDES, nms_thresh=0.45,
                 nms_topk=400, post_nms=100, **kwargs):
        super().__init__(**kwargs)
        self.classes = classes
        self.nms_thresh = nms_thresh
        self.nms_topk = nms_topk
        self.post_nms = post_nms
        self.stages = nn.HybridSequential()
        for s in stages:
            self.stages.add(s)
        self.yolo_blocks = nn.HybridSequential()
        self.yolo_outputs = nn.HybridSequential()
        self.transitions = nn.HybridSequential()
        # build top-down: largest stride first
        for i, (ch, anc, st) in enumerate(
                zip(channels, reversed(anchors), reversed(strides))):
            self.yolo_blocks.add(YOLODetectionBlockV3(ch))
            self.yolo_outputs.add(YOLOOutputV3(classes, anc, st))
            if i < len(channels) - 1:
                self.transitions.add(_conv2d(ch // 2, 1, 0, 1))
        self.upsample = _Upsample(2)

    def hybrid_forward(self, F, x):
        from .... import _tape
        from ....ndarray import contrib
        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        feats = feats[::-1]         # C5, C4, C3
        all_preds, all_boxes, all_scores = [], [], []
        route = None
        for i, (block, output) in enumerate(
                zip(self.yolo_blocks, self.yolo_outputs)):
            f = feats[i]
            if route is not None:
                up = self.upsample(self.transitions[i - 1](route))
                f = F.concat(up, f, dim=1)
            route, tip = block(f)
            pred, boxes, scores = output(tip)
            all_preds.append(pred)
            all_boxes.append(boxes)
            all_scores.append(scores)
        if _tape.is_training():
            return all_preds, all_boxes, all_scores
        boxes = F.concat(*all_boxes, dim=1)       # (B, N, 4)
        scores = F.concat(*all_scores, dim=1)     # (B, N, C)
        # per-class detections: take best class per box (compact decode)
        cls_id = F.argmax(scores, axis=-1)
        best = F.max(scores, axis=-1)
        dets = F.concat(F.expand_dims(cls_id, -1), F.expand_dims(best, -1),
                        boxes, dim=-1)
        dets = contrib.box_nms(dets, overlap_thresh=self.nms_thresh,
                               valid_thresh=0.01, topk=self.nms_topk,
                               coord_start=2, score_index=1, id_index=0)
        ids = F.slice_axis(dets, axis=-1, begin=0, end=1)
        sc = F.slice_axis(dets, axis=-1, begin=1, end=2)
        bb = F.slice_axis(dets, axis=-1, begin=2, end=6)
        return ids, sc, bb


def yolo3_darknet53(classes=80, **kwargs):
    """YOLOv3 with Darknet-53 base (gluoncv yolo3_darknet53_coco)."""
    base = darknet53()
    feats = list(base.features._children.values())
    # stage splits: through C3 (8-block stage), C4, C5
    s1 = nn.HybridSequential()
    for b in feats[:15]:
        s1.add(b)
    s2 = nn.HybridSequential()
    for b in feats[15:24]:
        s2.add(b)
    s3 = nn.HybridSequential()
    for b in feats[24:]:
        s3.add(b)
    return YOLOV3([s1, s2, s3], classes=classes, **kwargs)
