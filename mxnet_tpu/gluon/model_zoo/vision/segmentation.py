"""Semantic segmentation zoo: FCN / PSPNet / DeepLabV3 (GluonCV parity:
gluoncv/model_zoo/{fcn.py,pspnet.py,deeplabv3.py}, segbase.py).

Backbone is a dilated ResNetV1b (stages 3/4 use dilation 2/4, output stride
8) — the GluonCV `resnet50_v1b` pattern. All heads are HybridBlocks; the
final bilinear upsample is `contrib.BilinearResize2D` (static target size).
SyncBatchNorm can be swapped in via `norm_layer` for multi-chip training
(gluon.contrib.nn.SyncBatchNorm reduces stats over the mesh 'dp' axis).
"""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn

__all__ = ["ResNetV1b", "resnet18_v1b", "resnet34_v1b", "resnet50_v1b",
           "resnet101_v1b",
           "FCN", "PSPNet", "DeepLabV3",
           "get_fcn", "get_psp", "get_deeplab"]


class BasicBlockV1b(HybridBlock):
    """Two-3x3 residual block, stride on the first conv (gluoncv
    resnetv1b.py BasicBlockV1b)."""

    expansion = 1

    def __init__(self, planes, strides=1, dilation=1, downsample=None,
                 previous_dilation=1, norm_layer=nn.BatchNorm, **kwargs):
        super().__init__(**kwargs)
        self.conv1 = nn.Conv2D(planes, kernel_size=3, strides=strides,
                               padding=dilation, dilation=dilation,
                               use_bias=False)
        self.bn1 = norm_layer()
        self.conv2 = nn.Conv2D(planes, kernel_size=3, strides=1,
                               padding=previous_dilation,
                               dilation=previous_dilation, use_bias=False)
        self.bn2 = norm_layer()
        self.relu = nn.Activation("relu")
        self.downsample = downsample

    def hybrid_forward(self, F, x):
        residual = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu(out + residual)


class BottleneckV1b(HybridBlock):
    expansion = 4

    def __init__(self, planes, strides=1, dilation=1, downsample=None,
                 previous_dilation=1, norm_layer=nn.BatchNorm, **kwargs):
        super().__init__(**kwargs)
        self.conv1 = nn.Conv2D(planes, kernel_size=1, use_bias=False)
        self.bn1 = norm_layer()
        self.conv2 = nn.Conv2D(planes, kernel_size=3, strides=strides,
                               padding=dilation, dilation=dilation,
                               use_bias=False)
        self.bn2 = norm_layer()
        self.conv3 = nn.Conv2D(planes * 4, kernel_size=1, use_bias=False)
        self.bn3 = norm_layer()
        self.relu = nn.Activation("relu")
        self.downsample = downsample

    def hybrid_forward(self, F, x):
        residual = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            residual = self.downsample(x)
        return self.relu(out + residual)


class ResNetV1b(HybridBlock):
    """Dilated ResNet backbone (gluoncv resnetv1b.py), output stride 8."""

    def __init__(self, layers, classes=1000, dilated=True,
                 norm_layer=nn.BatchNorm, deep_stem=False,
                 block=BottleneckV1b, **kwargs):
        super().__init__(**kwargs)
        self._block = block
        self.conv1 = nn.Conv2D(64, kernel_size=7, strides=2, padding=3,
                               use_bias=False)
        self.bn1 = norm_layer()
        self.relu = nn.Activation("relu")
        self.maxpool = nn.MaxPool2D(pool_size=3, strides=2, padding=1)
        planes = (64, 128, 256, 512)
        strides = (1, 2, 1, 1) if dilated else (1, 2, 2, 2)
        dilations = (1, 1, 2, 4) if dilated else (1, 1, 1, 1)
        self.layer1 = self._make_layer(planes[0], layers[0], strides[0],
                                       dilations[0], norm_layer)
        self.layer2 = self._make_layer(planes[1], layers[1], strides[1],
                                       dilations[1], norm_layer)
        self.layer3 = self._make_layer(planes[2], layers[2], strides[2],
                                       dilations[2], norm_layer)
        self.layer4 = self._make_layer(planes[3], layers[3], strides[3],
                                       dilations[3], norm_layer)
        self.avgpool = nn.GlobalAvgPool2D()
        self.fc = nn.Dense(classes)

    def _make_layer(self, planes, blocks, strides, dilation, norm_layer):
        block = self._block
        layer = nn.HybridSequential()
        in_c = getattr(self, "_in_c", 64)
        if strides != 1 or in_c != planes * block.expansion:
            downsample = nn.HybridSequential()
            downsample.add(nn.Conv2D(planes * block.expansion, kernel_size=1,
                                     strides=strides, use_bias=False))
            downsample.add(norm_layer())
        else:   # identity shortcut (gluoncv: no downsample when shapes match)
            downsample = None
        self._in_c = planes * block.expansion
        first_dil = 1 if dilation in (1, 2) else 2
        layer.add(block(planes, strides, first_dil, downsample,
                        previous_dilation=dilation, norm_layer=norm_layer))
        for _ in range(1, blocks):
            layer.add(block(planes, 1, dilation, previous_dilation=dilation,
                            norm_layer=norm_layer))
        return layer

    def hybrid_forward(self, F, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        c1 = self.layer1(x)
        c2 = self.layer2(c1)
        c3 = self.layer3(c2)
        c4 = self.layer4(c3)
        x = self.avgpool(c4)
        return self.fc(F.flatten(x))

    def extract(self, x):
        """Return (c3, c4) feature maps for segmentation heads."""
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer1(x)
        x = self.layer2(x)
        c3 = self.layer3(x)
        c4 = self.layer4(c3)
        return c3, c4


def resnet18_v1b(**kwargs):
    return ResNetV1b([2, 2, 2, 2], block=BasicBlockV1b, **kwargs)


def resnet34_v1b(**kwargs):
    return ResNetV1b([3, 4, 6, 3], block=BasicBlockV1b, **kwargs)


def resnet50_v1b(**kwargs):
    return ResNetV1b([3, 4, 6, 3], **kwargs)


def resnet101_v1b(**kwargs):
    return ResNetV1b([3, 4, 23, 3], **kwargs)


class _FCNHead(HybridBlock):
    def __init__(self, nclass, channels=512, norm_layer=nn.BatchNorm,
                 **kwargs):
        super().__init__(**kwargs)
        self.block = nn.HybridSequential()
        self.block.add(nn.Conv2D(channels // 4, kernel_size=3, padding=1,
                                 use_bias=False))
        self.block.add(norm_layer())
        self.block.add(nn.Activation("relu"))
        self.block.add(nn.Dropout(0.1))
        self.block.add(nn.Conv2D(nclass, kernel_size=1))

    def hybrid_forward(self, F, x):
        return self.block(x)


class SegBaseModel(HybridBlock):
    def __init__(self, nclass, backbone="resnet50", aux=True,
                 norm_layer=nn.BatchNorm, crop_size=480, **kwargs):
        super().__init__(**kwargs)
        self.nclass = nclass
        self.aux = aux
        self.crop_size = crop_size
        if backbone == "resnet50":
            self.base = resnet50_v1b(norm_layer=norm_layer)
        elif backbone == "resnet101":
            self.base = resnet101_v1b(norm_layer=norm_layer)
        else:
            raise MXNetError(f"unknown backbone {backbone}")

    def _resize(self, x, like):
        from ....ndarray import contrib
        return contrib.BilinearResize2D(x, height=like.shape[2],
                                        width=like.shape[3])

    def predict(self, x):
        from .... import _tape
        prev = _tape.set_training(False)
        try:
            out = self(x)
        finally:
            _tape.set_training(prev)
        return out[0] if isinstance(out, (tuple, list)) else out

    def evaluate(self, x):
        return self.predict(x)


class FCN(SegBaseModel):
    """Fully Convolutional Network (gluoncv fcn.py FCN8s-style head)."""

    def __init__(self, nclass, backbone="resnet50", aux=True,
                 norm_layer=nn.BatchNorm, **kwargs):
        super().__init__(nclass, backbone, aux, norm_layer, **kwargs)
        self.head = _FCNHead(nclass, 2048, norm_layer)
        if aux:
            self.auxlayer = _FCNHead(nclass, 1024, norm_layer)

    def hybrid_forward(self, F, x):
        from .... import _tape
        c3, c4 = self.base.extract(x)
        out = self._resize(self.head(c4), x)
        if self.aux and _tape.is_training():
            return out, self._resize(self.auxlayer(c3), x)
        return out


class _PyramidPooling(HybridBlock):
    def __init__(self, norm_layer=nn.BatchNorm, **kwargs):
        super().__init__(**kwargs)
        self.convs = nn.HybridSequential()
        for _ in range(4):
            blk = nn.HybridSequential()
            blk.add(nn.Conv2D(512, kernel_size=1, use_bias=False))
            blk.add(norm_layer())
            blk.add(nn.Activation("relu"))
            self.convs.add(blk)

    def hybrid_forward(self, F, x):
        from ....ndarray import contrib
        h, w = x.shape[2], x.shape[3]
        outs = [x]
        for size, conv in zip((1, 2, 3, 6), self.convs):
            p = contrib.AdaptiveAvgPooling2D(x, output_size=size)
            p = conv(p)
            outs.append(contrib.BilinearResize2D(p, height=h, width=w))
        return F.concat(*outs, dim=1)


class PSPNet(SegBaseModel):
    """Pyramid Scene Parsing (gluoncv pspnet.py)."""

    def __init__(self, nclass, backbone="resnet50", aux=True,
                 norm_layer=nn.BatchNorm, **kwargs):
        super().__init__(nclass, backbone, aux, norm_layer, **kwargs)
        self.psp = _PyramidPooling(norm_layer)
        self.head = nn.HybridSequential()
        self.head.add(nn.Conv2D(512, kernel_size=3, padding=1,
                                use_bias=False))
        self.head.add(norm_layer())
        self.head.add(nn.Activation("relu"))
        self.head.add(nn.Dropout(0.1))
        self.head.add(nn.Conv2D(nclass, kernel_size=1))
        if aux:
            self.auxlayer = _FCNHead(nclass, 1024, norm_layer)

    def hybrid_forward(self, F, x):
        from .... import _tape
        c3, c4 = self.base.extract(x)
        out = self._resize(self.head(self.psp(c4)), x)
        if self.aux and _tape.is_training():
            return out, self._resize(self.auxlayer(c3), x)
        return out


class _ASPP(HybridBlock):
    """Atrous spatial pyramid pooling (deeplabv3.py), rates 12/24/36."""

    def __init__(self, norm_layer=nn.BatchNorm, rates=(12, 24, 36), **kwargs):
        super().__init__(**kwargs)
        out_ch = 256
        self.b0 = nn.HybridSequential()
        self.b0.add(nn.Conv2D(out_ch, kernel_size=1, use_bias=False))
        self.b0.add(norm_layer())
        self.b0.add(nn.Activation("relu"))
        self.branches = nn.HybridSequential()
        for r in rates:
            blk = nn.HybridSequential()
            blk.add(nn.Conv2D(out_ch, kernel_size=3, padding=r, dilation=r,
                              use_bias=False))
            blk.add(norm_layer())
            blk.add(nn.Activation("relu"))
            self.branches.add(blk)
        self.gap_conv = nn.HybridSequential()
        self.gap_conv.add(nn.Conv2D(out_ch, kernel_size=1, use_bias=False))
        self.gap_conv.add(norm_layer())
        self.gap_conv.add(nn.Activation("relu"))
        self.project = nn.HybridSequential()
        self.project.add(nn.Conv2D(out_ch, kernel_size=1, use_bias=False))
        self.project.add(norm_layer())
        self.project.add(nn.Activation("relu"))
        self.project.add(nn.Dropout(0.5))

    def hybrid_forward(self, F, x):
        from ....ndarray import contrib
        h, w = x.shape[2], x.shape[3]
        outs = [self.b0(x)]
        for blk in self.branches:
            outs.append(blk(x))
        gap = contrib.AdaptiveAvgPooling2D(x, output_size=1)
        gap = self.gap_conv(gap)
        outs.append(contrib.BilinearResize2D(gap, height=h, width=w))
        return self.project(F.concat(*outs, dim=1))


class DeepLabV3(SegBaseModel):
    """DeepLabV3 (gluoncv deeplabv3.py)."""

    def __init__(self, nclass, backbone="resnet50", aux=True,
                 norm_layer=nn.BatchNorm, **kwargs):
        super().__init__(nclass, backbone, aux, norm_layer, **kwargs)
        self.aspp = _ASPP(norm_layer)
        self.head = nn.HybridSequential()
        self.head.add(nn.Conv2D(256, kernel_size=3, padding=1,
                                use_bias=False))
        self.head.add(norm_layer())
        self.head.add(nn.Activation("relu"))
        self.head.add(nn.Conv2D(nclass, kernel_size=1))
        if aux:
            self.auxlayer = _FCNHead(nclass, 1024, norm_layer)

    def hybrid_forward(self, F, x):
        from .... import _tape
        c3, c4 = self.base.extract(x)
        out = self._resize(self.head(self.aspp(c4)), x)
        if self.aux and _tape.is_training():
            return out, self._resize(self.auxlayer(c3), x)
        return out


def get_fcn(nclass=21, backbone="resnet50", **kwargs):
    return FCN(nclass, backbone, **kwargs)


def get_psp(nclass=21, backbone="resnet50", **kwargs):
    return PSPNet(nclass, backbone, **kwargs)


def get_deeplab(nclass=21, backbone="resnet50", **kwargs):
    return DeepLabV3(nclass, backbone, **kwargs)
