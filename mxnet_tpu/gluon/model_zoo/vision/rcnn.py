"""Faster R-CNN (GluonCV parity: gluoncv/model_zoo/rcnn/faster_rcnn/).

TPU-first: every stage is static-shape. Proposal selection is top-k (fixed
k) + fixed-trip NMS — low-scoring slots survive as masked rows instead of
being dropped, so the whole detector is one jittable program (the
reference's dynamic-shape `contrib.Proposal` op cannot tile onto the MXU).
ROIAlign is the vectorised bilinear gather from mx.nd.contrib.
"""
from __future__ import annotations

import math

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn
from .segmentation import resnet50_v1b

__all__ = ["RPN", "FasterRCNN", "faster_rcnn_resnet50_v1b"]


class RPNAnchorGenerator(HybridBlock):
    """Absolute-pixel anchors at one stride (gluoncv rpn/anchor.py)."""

    def __init__(self, stride=16, scales=(8, 16, 32), ratios=(0.5, 1, 2),
                 base_size=16, **kwargs):
        super().__init__(**kwargs)
        self._stride = stride
        shapes = []
        for s in scales:
            for r in ratios:
                size = (base_size * s) ** 2 / r
                w = math.sqrt(size)
                h = w * r
                shapes.append((w, h))
        self._shapes = shapes

    @property
    def num_anchors(self):
        return len(self._shapes)

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        from ....ndarray.ndarray import apply_nary
        stride, shapes = self._stride, self._shapes

        def fn(d):
            h, w = d.shape[-2], d.shape[-1]
            cy = (jnp.arange(h) + 0.5) * stride
            cx = (jnp.arange(w) + 0.5) * stride
            ws = jnp.asarray([s[0] for s in shapes])
            hs = jnp.asarray([s[1] for s in shapes])
            cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
            cyg = cyg[..., None]
            cxg = cxg[..., None]
            anch = jnp.stack([cxg - ws / 2, cyg - hs / 2,
                              cxg + ws / 2, cyg + hs / 2], axis=-1)
            return anch.reshape(1, -1, 4)

        return apply_nary(fn, [x], name="rpn_anchors")


class RPN(HybridBlock):
    """Region proposal network head + static proposal selection."""

    def __init__(self, channels=256, stride=16, pre_nms=2000, post_nms=300,
                 nms_thresh=0.7, scales=(8, 16, 32), ratios=(0.5, 1, 2),
                 base_size=16, **kwargs):
        super().__init__(**kwargs)
        self._pre_nms = pre_nms
        self._post_nms = post_nms
        self._nms_thresh = nms_thresh
        with self.name_scope():
            self.anchor_gen = RPNAnchorGenerator(
                stride=stride, scales=scales, ratios=ratios,
                base_size=base_size)
            na = self.anchor_gen.num_anchors
            self.conv = nn.Conv2D(channels, 3, 1, 1, activation="relu")
            self.score = nn.Conv2D(na, 1, 1, 0)
            self.loc = nn.Conv2D(na * 4, 1, 1, 0)

    def hybrid_forward(self, F, feat, im_size):
        import jax
        import jax.numpy as jnp
        from ....ndarray.ndarray import apply_nary
        x = self.conv(feat)
        score = self.score(x)       # (B, na, H, W)
        loc = self.loc(x)           # (B, na*4, H, W)
        anchors = self.anchor_gen(feat)
        pre_nms, post_nms = self._pre_nms, self._post_nms
        nms_thresh = self._nms_thresh
        imh, imw = im_size

        def proposals(sc, lc, anc):
            b = sc.shape[0]
            na = anc.shape[1]
            sc = jax.nn.sigmoid(sc.transpose(0, 2, 3, 1).reshape(b, -1))
            lc = lc.transpose(0, 2, 3, 1).reshape(b, -1, 4)
            a = anc[0]
            aw = a[:, 2] - a[:, 0]
            ah = a[:, 3] - a[:, 1]
            ax = (a[:, 0] + a[:, 2]) / 2
            ay = (a[:, 1] + a[:, 3]) / 2

            def one(s, l):
                ox = l[:, 0] * aw + ax
                oy = l[:, 1] * ah + ay
                ow = jnp.exp(jnp.clip(l[:, 2], -10, 10)) * aw / 2
                oh = jnp.exp(jnp.clip(l[:, 3], -10, 10)) * ah / 2
                boxes = jnp.stack(
                    [jnp.clip(ox - ow, 0, imw), jnp.clip(oy - oh, 0, imh),
                     jnp.clip(ox + ow, 0, imw), jnp.clip(oy + oh, 0, imh)],
                    axis=-1)
                k = min(pre_nms, boxes.shape[0])
                top_s, idx = jax.lax.top_k(s, k)
                top_b = boxes[idx]
                # fixed-trip greedy NMS on the top-k
                def iou_row(i, keep):
                    bi = top_b[i]
                    tl = jnp.maximum(top_b[:, :2], bi[:2])
                    br = jnp.minimum(top_b[:, 2:], bi[2:])
                    wh = jnp.maximum(br - tl, 0.0)
                    inter = wh[:, 0] * wh[:, 1]
                    area = jnp.maximum(
                        (top_b[:, 2] - top_b[:, 0]) *
                        (top_b[:, 3] - top_b[:, 1]), 1e-12)
                    ai = jnp.maximum((bi[2] - bi[0]) * (bi[3] - bi[1]),
                                     1e-12)
                    iou = inter / (area + ai - inter)
                    sup = (iou > nms_thresh) & (jnp.arange(k) > i)
                    return jnp.where(keep[i], keep & ~sup, keep)

                keep = jax.lax.fori_loop(0, k, iou_row, jnp.ones(k, bool))
                masked = jnp.where(keep, top_s, -1.0)
                # small images can have fewer anchors than post_nms
                sel_s, sel_i = jax.lax.top_k(masked, min(post_nms, k))
                return top_b[sel_i], sel_s

            rois, scores = jax.vmap(one)(sc, lc)
            return rois, scores

        rois, roi_scores = apply_nary(proposals, [score, loc, anchors],
                                      n_out=2, name="rpn_proposals")
        return score, loc, anchors, rois, roi_scores


class FasterRCNN(HybridBlock):
    """Two-stage detector: RPN proposals -> ROIAlign -> box head.

    Train mode returns (cls_pred, box_pred, rois, rpn_score, rpn_loc,
    anchors); eval returns (ids, scores, bboxes) with per-roi best class.
    """

    def __init__(self, classes, backbone=None, roi_size=(7, 7), stride=16,
                 post_nms=300, nms_thresh=0.3, score_thresh=0.05,
                 rpn_scales=(8, 16, 32), rpn_ratios=(0.5, 1, 2),
                 rpn_base_size=16, **kwargs):
        super().__init__(**kwargs)
        self.classes = list(classes)
        self.num_classes = len(self.classes)
        self._roi_size = roi_size
        self._stride = stride
        self._nms_thresh = nms_thresh
        self._score_thresh = score_thresh
        with self.name_scope():
            self.base = backbone or resnet50_v1b(dilated=False)
            # only conv1..layer3 (C4) feed the detector — drop the
            # classification tail so it is neither allocated nor saved
            for tail in ("layer4", "avgpool", "fc"):
                if tail in self.base._children:
                    self.base._children.pop(tail)
                    object.__delattr__(self.base, tail)
            self.rpn = RPN(stride=stride, post_nms=post_nms,
                           scales=rpn_scales, ratios=rpn_ratios,
                           base_size=rpn_base_size)
            self.top_features = nn.HybridSequential()
            self.top_features.add(nn.Dense(1024, activation="relu",
                                           flatten=True))
            self.top_features.add(nn.Dense(1024, activation="relu"))
            self.class_predictor = nn.Dense(self.num_classes + 1)
            self.box_predictor = nn.Dense(self.num_classes * 4)

    def _features(self, x):
        b = self.base
        y = b.maxpool(b.relu(b.bn1(b.conv1(x))))
        y = b.layer1(y)
        y = b.layer2(y)
        return b.layer3(y)      # C4, stride 16

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp
        from .... import _tape
        from ....ndarray import contrib
        from ....ndarray.ndarray import apply_nary
        im_h, im_w = x.shape[2], x.shape[3]
        feat = self._features(x)
        rpn_score, rpn_loc, anchors, rois, roi_scores = \
            self.rpn(feat, (im_h, im_w))
        b, n_roi = rois.shape[0], rois.shape[1]
        stride = self._stride

        def to_roi5(r):
            # approximate joint training (Faster R-CNN paper §3.2): the
            # box head does not backprop through proposal coordinates
            import jax
            r = jax.lax.stop_gradient(r)
            batch_idx = jnp.repeat(jnp.arange(b, dtype=r.dtype), n_roi)
            return jnp.concatenate(
                [batch_idx[:, None], r.reshape(-1, 4)], axis=-1)

        rois5 = apply_nary(to_roi5, [rois], name="roi5")
        pooled = contrib.ROIAlign(feat, rois5, pooled_size=self._roi_size,
                                  spatial_scale=1.0 / stride,
                                  sample_ratio=2)
        top = self.top_features(pooled)
        cls_pred = self.class_predictor(top)    # (B*R, C+1)
        box_pred = self.box_predictor(top)      # (B*R, C*4)
        if _tape.is_training():
            return cls_pred, box_pred, rois, rpn_score, rpn_loc, anchors
        ncls = self.num_classes
        score_thresh = self._score_thresh

        def decode(cp, bp, r):
            prob = jnp.exp(jnp.clip(cp - cp.max(-1, keepdims=True), -30, 0))
            prob = prob / prob.sum(-1, keepdims=True)
            best = jnp.argmax(prob[:, 1:], axis=-1)       # skip background
            best_p = jnp.max(prob[:, 1:], axis=-1)
            deltas = bp.reshape(-1, ncls, 4)
            d = jnp.take_along_axis(
                deltas, best[:, None, None].repeat(4, -1), axis=1)[:, 0]
            rf = r.reshape(-1, 4)
            rw = rf[:, 2] - rf[:, 0]
            rh = rf[:, 3] - rf[:, 1]
            rx = (rf[:, 0] + rf[:, 2]) / 2
            ry = (rf[:, 1] + rf[:, 3]) / 2
            ox = d[:, 0] * 0.1 * rw + rx
            oy = d[:, 1] * 0.1 * rh + ry
            ow = jnp.exp(jnp.clip(d[:, 2] * 0.2, -10, 10)) * rw / 2
            oh = jnp.exp(jnp.clip(d[:, 3] * 0.2, -10, 10)) * rh / 2
            boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
            ids = jnp.where(best_p > score_thresh,
                            best.astype(boxes.dtype), -1.0)
            det = jnp.concatenate([ids[:, None], best_p[:, None], boxes],
                                  axis=-1)
            return det.reshape(b, n_roi, 6)

        dets = apply_nary(decode, [cls_pred, box_pred, rois], name="rcnn_decode")
        dets = contrib.box_nms(dets, overlap_thresh=self._nms_thresh,
                               valid_thresh=score_thresh, topk=100,
                               coord_start=2, score_index=1, id_index=0)
        ids = F.slice_axis(dets, axis=-1, begin=0, end=1)
        scores = F.slice_axis(dets, axis=-1, begin=1, end=2)
        bboxes = F.slice_axis(dets, axis=-1, begin=2, end=6)
        return ids, scores, bboxes


_VOC_CLASSES = tuple(f"class_{i}" for i in range(20))


def faster_rcnn_resnet50_v1b(classes=_VOC_CLASSES, **kwargs):
    """gluoncv faster_rcnn_resnet50_v1b_voc parity."""
    return FasterRCNN(classes, **kwargs)
