"""Gluon ``Parameter`` / ``ParameterDict`` / ``Constant``.

Reference: python/mxnet/gluon/parameter.py (SURVEY.md §2.2 "Gluon core").

TPU-native deltas from the reference:
  - A Parameter owns ONE NDArray, not per-context copies: multi-device data
    parallelism is expressed by *sharding* that one array over a mesh
    (jax.sharding), not by replicating Python handles (SURVEY.md §2.5 DP row).
  - Deferred init works the same way (shape with 0s resolved at first
    forward).
  - ``stype``/``grad_stype`` accepted; row_sparse grads fall back to dense
    (XLA apply is dense) with the flag recorded for the KVStore path.
"""
from __future__ import annotations

import re
import warnings

import numpy as _np
import jax.numpy as jnp

from ..base import MXNetError
from ..context import Context, current_context, cpu
from ..ndarray.ndarray import NDArray, zeros as nd_zeros
from ..ndarray import utils as nd_utils
from .. import initializer as init_mod

__all__ = ["Parameter", "ParameterDict", "Constant", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Raised when a parameter's data is requested before shapes are known.
    Reference: gluon/parameter.py DeferredInitializationError."""


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = None
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        if stype not in ("default", "row_sparse", "csr"):
            raise MXNetError(f"invalid stype {stype}")
        self._stype = stype
        self._grad_stype = grad_stype
        self._data = None          # NDArray
        self._deferred_init = None  # (init, ctx, default_init)
        self._ctx = None
        self._shard_spec = None    # parallel.PartitionSpec-like annotation
        self.grad_req = grad_req

    # ------------------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"grad_req must be write/add/null, got {req}")
        if not self._differentiable:
            req = "null"
        self._grad_req = req
        if self._data is not None:
            self._data.attach_grad(req, stype=self._grad_stype)

    @property
    def stype(self):
        return self._stype

    @property
    def grad_stype(self):
        return self._grad_stype

    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass.")
        raise MXNetError(
            f"Parameter '{self.name}' has not been initialized. You should "
            "first call block.initialize() before using it.")

    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        default_init = default_init or init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, (list, tuple)):
            # reference API took a ctx list for multi-GPU; one sharded array
            # covers that here — keep the first ctx
            ctx = ctx[0] if ctx else current_context()
        self._ctx = ctx
        if self.shape is None or any(s <= 0 for s in self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                f"Cannot initialize Parameter '{self.name}' because it has "
                f"invalid shape {self.shape} and deferred init is not allowed.")
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, init, ctx, default_init):
        data = nd_zeros(self.shape, ctx=ctx, dtype=self.dtype)
        initializer = init if init is not None else \
            (self.init if self.init is not None else default_init)
        if isinstance(initializer, str):
            initializer = init_mod.create(initializer)
        initializer(init_mod.InitDesc(self.name), data)
        self._data = data
        self._deferred_init = None
        if self._grad_req != "null":
            self._data.attach_grad(self._grad_req,
                                   stype=self._grad_stype)

    def _finish_deferred_init(self, in_shape=None):
        """Called by layers once the input shape is known."""
        if self._deferred_init is None:
            return
        if in_shape is not None:
            new_shape = tuple(s if s > 0 else i
                              for s, i in zip(self.shape, in_shape))
            self.shape = new_shape
        if any(s <= 0 for s in self.shape):
            raise MXNetError(
                f"deferred init of '{self.name}' still has unknown dims "
                f"{self.shape}")
        init_, ctx, default_init = self._deferred_init
        self._finish_init(init_, ctx, default_init)

    def shape_updated(self, shape):
        """Merge newly inferred dims into a partially-known shape."""
        if self.shape is None:
            self.shape = tuple(shape)
            return
        merged = []
        for s, n in zip(self.shape, shape):
            if s > 0 and n > 0 and s != n:
                raise MXNetError(
                    f"inferred shape {shape} incompatible with declared "
                    f"{self.shape} for parameter {self.name}")
            merged.append(s if s > 0 else n)
        self.shape = tuple(merged)

    # ------------------------------------------------------------------
    def data(self, ctx=None):
        self._check_initialized()
        if _USE_ORDER_RECORDERS:
            for rec in _USE_ORDER_RECORDERS:
                rec.note(self)
        override = _TRACE_BINDINGS.get(id(self))
        if override is not None:
            return override
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad_req == "null":
            raise MXNetError(
                f"Cannot get gradient array for Parameter '{self.name}' "
                "because grad_req='null'")
        return self._data.grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None and self._deferred_init is not None:
            return [self._deferred_init[1]]
        self._check_initialized()
        return [self._ctx or current_context()]

    def zero_grad(self):
        if self._data is not None and self._data._grad is not None:
            if self._grad_stype == "row_sparse":
                self._data._grad = None    # next backward re-installs O(nnz)
            else:
                self._data._grad = jnp.zeros(self._data.shape,
                                             self._data.data.dtype)
            self._data._grad_reduced = False   # new accumulation cycle

    def set_data(self, data):
        if isinstance(data, NDArray):
            data = data.data
        else:
            data = jnp.asarray(data)
        if self._data is None:
            self.shape = tuple(data.shape)
            self._deferred_init = None
            self._data = NDArray(data, self._ctx or current_context())
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req,
                                       stype=self._grad_stype)
            return
        if tuple(data.shape) != self.shape:
            raise MXNetError(
                f"set_data shape {tuple(data.shape)} != param shape {self.shape}")
        self._data._set_data(data.astype(self._data.data.dtype))

    def reset_ctx(self, ctx):
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        if self._data is not None:
            self._data = self._data.as_in_context(self._ctx)
            if self._grad_req != "null":
                self._data.attach_grad(self._grad_req,
                                       stype=self._grad_stype)

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            had_grad = self._data._grad is not None
            self._data = self._data.astype(dtype)
            if had_grad or self._grad_req != "null":
                self._data.attach_grad(self._grad_req,
                                       stype=self._grad_stype)

    # sharding annotation for pjit paths (TPU-native extension)
    def shard(self, spec):
        self._shard_spec = spec
        return self

    @property
    def shard_spec(self):
        return self._shard_spec

    def var(self):
        from ..symbol import Symbol
        return Symbol._var(self.name)

    def __repr__(self):
        return (f"Parameter {self.name} (shape={self.shape}, "
                f"dtype={self.dtype})")


class Constant(Parameter):
    """Non-differentiable constant parameter (reference gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            from ..ndarray.ndarray import array
            value = array(value)
        self._value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.data.dtype), differentiable=False,
                         init="zeros")

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        self._data = self._value
        self._deferred_init = None


# trace-time parameter value overrides (set by CachedOp while tracing)
_TRACE_BINDINGS = {}

# active forward use-order recorders (see record_param_use); a plain list
# so Parameter.data() pays one falsy check when none are active
_USE_ORDER_RECORDERS = []


class record_param_use:
    """Scope recording the order parameters are FIRST accessed in a
    forward — the reverse of backward gradient-ready order, which is
    what a backward-ordered ``zero.BucketPlan(fill_order=...)`` needs
    (parallel.DataParallelTrainer probes one abstract forward under
    this to plan overlap-friendly buckets)."""

    def __init__(self):
        self.order = []          # Parameter objects, first-use order
        self._seen = set()

    def note(self, param):
        if id(param) not in self._seen:
            self._seen.add(id(param))
            self.order.append(param)

    def __enter__(self):
        _USE_ORDER_RECORDERS.append(self)
        return self

    def __exit__(self, *exc):
        _USE_ORDER_RECORDERS.remove(self)
        return False


class _bind_params:
    """Context manager mapping Parameter -> tracer array during jit trace."""

    def __init__(self, mapping):
        self.mapping = mapping

    def __enter__(self):
        for p, arr in self.mapping.items():
            _TRACE_BINDINGS[id(p)] = arr
        return self

    def __exit__(self, *exc):
        for p in self.mapping:
            _TRACE_BINDINGS.pop(id(p), None)
        return False


class ParameterDict:
    """Ordered name->Parameter mapping with a shared prefix.
    Reference: gluon/parameter.py ParameterDict."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __repr__(self):
        s = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{s}\n)"

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name in self._params:
            param = self._params[name]
            # merge shape info
            if kwargs.get("shape") is not None and param.shape is not None:
                param.shape_updated(tuple(kwargs["shape"]))
            return param
        if self._shared is not None and name in self._shared:
            self._params[name] = self._shared[name]
            return self._shared[name]
        param = Parameter(name, **kwargs)
        self._params[name] = param
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        if name in self._params:
            return self._params[name]
        if value is None:
            raise MXNetError(f"No constant named '{name}'")
        const = Constant(name, value)
        self._params[name] = const
        return const

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        default = init or init_mod.Uniform()
        for param in self._params.values():
            param.initialize(None, ctx, default, force_reinit=force_reinit)

    def zero_grad(self):
        for param in self._params.values():
            param.zero_grad()

    def reset_ctx(self, ctx):
        for param in self._params.values():
            param.reset_ctx(ctx)

    def setattr(self, name, value):
        for param in self._params.values():
            setattr(param, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self._params.values():
            block = param.data()
            name = param.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = block
        nd_utils.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = nd_utils.load(filename)
        if restore_prefix:
            loaded = {restore_prefix + k: v for k, v in loaded.items()}
        # strip legacy arg:/aux: prefixes
        loaded = {_strip_ref_prefix(k): v for k, v in loaded.items()}
        for name, param in self._params.items():
            if name not in loaded:
                if not allow_missing:
                    raise MXNetError(
                        f"Parameter '{name}' is missing in file '{filename}'")
                continue
            param.set_data(loaded[name])
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(
                    f"Parameters {sorted(extra)} in file are not present in "
                    f"this ParameterDict (set ignore_extra=True to skip)")


def _strip_ref_prefix(name):
    for p in ("arg:", "aux:"):
        if name.startswith(p):
            return name[len(p):]
    return name
