"""``gluon.contrib.data`` — contrib samplers & datasets.

Reference: python/mxnet/gluon/contrib/data/ (sampler.py IntervalSampler;
text.py WikiText datasets). The text datasets needed downloads; in this
zero-egress build they are gated like the other network-backed loaders
(`MXTPU_SYNTHETIC_DATA=1` covers vision; text corpora must be local).
"""
from __future__ import annotations

from ....base import MXNetError
from ...data.dataloader import Sampler

__all__ = ["IntervalSampler"]


class IntervalSampler(Sampler):
    """Sample i, i+k, i+2k, ... for each offset i in [0, k) — the
    strided-interleave sampler (reference contrib/data/sampler.py).

    With rollover=True (default) every element is visited once, offset
    by offset; with rollover=False only the offset-0 stride is yielded.
    """

    def __init__(self, length, interval, rollover=True):
        if interval > length:
            raise MXNetError(
                f"interval {interval} must be <= length {length}")
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        offsets = range(self._interval) if self._rollover else [0]
        for i in offsets:
            yield from range(i, self._length, self._interval)

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))
