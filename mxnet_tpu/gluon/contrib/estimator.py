"""``gluon.contrib.estimator`` — high-level fit API.

Reference [≥1.6]: python/mxnet/gluon/contrib/estimator/ (Estimator +
event handlers). Compact rebuild covering train/eval loops with handlers.
"""
from __future__ import annotations

import time

from ...base import MXNetError
from ... import metric as metric_mod
from ... import autograd
from ...telemetry import watchdog as _watchdog
from ..trainer import Trainer

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "LoggingHandler", "CheckpointHandler", "EarlyStoppingHandler",
           "ValidationHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            estimator.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            estimator.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    def __init__(self, metrics):
        self.metrics = metrics

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            if isinstance(m, metric_mod.Loss):
                m.update(0, loss)
            elif pred is not None:
                # the fused (DataParallelTrainer) path computes the loss
                # in-graph and never materializes predictions; only Loss
                # metrics can update there
                m.update(label, pred)


class LoggingHandler(TrainBegin, TrainEnd, EpochEnd):
    def __init__(self, log_interval="epoch", metrics=None):
        self.metrics = metrics or []

    def train_begin(self, estimator, *args, **kwargs):
        print("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        print("Training end")

    def epoch_end(self, estimator, *args, **kwargs):
        msgs = [f"{name}={val:.6f}" for m in self.metrics
                for name, val in m.get_name_value()]
        print(f"Epoch {estimator.current_epoch}: " + " ".join(msgs))


class ValidationHandler(EpochEnd):
    """Score val_data with eval_fn each epoch (reference
    estimator/event_handler.py ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period

    def epoch_end(self, estimator, *args, **kwargs):
        if (estimator.current_epoch + 1) % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class CheckpointHandler(TrainBegin, EpochEnd):
    """Save parameters (and trainer states) per epoch; optionally only on
    monitored-metric improvement (reference CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 save_best=False, mode="min", max_checkpoints=5):
        import os
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.mode = mode
        self.max_checkpoints = max_checkpoints
        self.best = None
        self.saved = []
        os.makedirs(model_dir, exist_ok=True)

    def _value(self):
        return self.monitor.get_name_value()[0][1]

    def _improved(self, val):
        if self.best is None:
            return True
        return val < self.best if self.mode == "min" else val > self.best

    def epoch_end(self, estimator, *args, **kwargs):
        import os
        path = os.path.join(
            self.model_dir,
            f"{self.model_prefix}-epoch{estimator.current_epoch}.params")
        estimator.net.save_parameters(path)
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            if os.path.exists(old):
                os.remove(old)
        if self.save_best and self.monitor is not None:
            val = self._value()
            if self._improved(val):
                self.best = val
                estimator.net.save_parameters(os.path.join(
                    self.model_dir, f"{self.model_prefix}-best.params"))


class EarlyStoppingHandler(EpochEnd):
    """Stop when the monitored metric stops improving (reference
    EarlyStoppingHandler): patience epochs of no improvement beyond
    min_delta end training."""

    def __init__(self, monitor, mode="min", patience=3, min_delta=0.0):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.bad_epochs = 0

    def epoch_end(self, estimator, *args, **kwargs):
        val = self.monitor.get_name_value()[0][1]
        improved = self.best is None or (
            self.best - val > self.min_delta if self.mode == "min"
            else val - self.best > self.min_delta)
        if improved:
            self.best = val
            self.bad_epochs = 0
        else:
            self.bad_epochs += 1
            if self.bad_epochs >= self.patience:
                estimator.stop_training = True


class Estimator:
    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None, val_metrics=None):
        self.net = net
        self.loss = loss
        self.train_metrics = train_metrics or [metric_mod.Accuracy()]
        if not isinstance(self.train_metrics, list):
            self.train_metrics = [self.train_metrics]
        self.trainer = trainer or Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.01})
        self.stop_training = False
        self.current_epoch = 0
        self.global_step = 0
        self.preempted = False

    def prepare_loss_and_metrics(self):
        return self.train_metrics

    def _resume(self, resume, manager):
        """Restore the newest valid checkpoint (or step ``resume`` when
        an int) into net + trainer + RNG; returns (start_epoch,
        skip_batches) — the mid-epoch cursor to fast-forward to."""
        if manager is None:
            raise MXNetError(
                'fit(resume=...) needs a checkpoint_manager')
        step = None if resume == "auto" else int(resume)
        manifest = manager.restore(step, params=self.net,
                                   trainer=self.trainer)
        if manifest is None:        # cold start: nothing saved yet
            return 0, 0
        self.global_step = int(manifest["step"])
        cursor = manifest.get("iterator", {})
        start_epoch = int(cursor.get("epoch", 0))
        self.current_epoch = start_epoch
        return start_epoch, int(cursor.get("batch", 0))

    def _epoch_source(self, train_data, prefetch_to_device, prefetch_depth):
        """Per-epoch batch source: with device prefetch requested, wrap
        ``train_data`` in an ``io.DevicePrefetcher`` (depth =
        ``prefetch_depth`` or the ``MXTPU_PREFETCH_DEPTH`` default) so
        batch N+1's H2D overlaps batch N's step.  Returns
        ``(iterable, closer)`` — the closer joins the worker thread at
        epoch end."""
        if not prefetch_to_device and prefetch_depth is None:
            return train_data, None
        from ...io import DevicePrefetcher
        pf = DevicePrefetcher(iter(train_data), depth=prefetch_depth)
        return pf, pf.close

    def _train_step_eager(self, data, label):
        """The classic gluon loop body: record/forward/backward/step.
        Returns (pred, loss)."""
        with autograd.record():
            pred = self.net(data)
            loss = self.loss(pred, label)
        loss.backward()
        self.trainer.step(data.shape[0])
        return pred, loss

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, resume=None, checkpoint_manager=None,
            checkpoint_every=None, prefetch_to_device=False,
            prefetch_depth=None, steps_per_call=None,
            elastic_controller=None, autoscaler=None):
        """Train; with ``checkpoint_manager`` the loop is preemption-safe:

        - ``checkpoint_every=N`` saves the full training state (params,
          optimizer state, lr/update counters, iterator cursor, RNG)
          every N global steps (async — the step never blocks on disk);
        - SIGTERM/SIGINT finish the in-flight step, force-sync a final
          checkpoint, and stop cleanly (``.preempted`` set);
        - ``resume="auto"`` (or an int step) restores the newest valid
          checkpoint — torn/corrupt ones are skipped — and fast-forwards
          the data iterator to the saved mid-epoch cursor.

        ``prefetch_to_device=True`` (or an explicit ``prefetch_depth=N``)
        stages batches onto the device through an ``io.DevicePrefetcher``
        so H2D overlaps the step; depth defaults to
        ``MXTPU_PREFETCH_DEPTH`` (2).

        ``steps_per_call=K`` (default: ``MXTPU_STEPS_PER_CALL``, 1) —
        multi-step compiled training (ISSUE 6): with a fused trainer
        (``parallel.DataParallelTrainer``, anything with ``step_multi``)
        the loop hands K batches at a time into ONE compiled dispatch.
        Handler calls, loss/metric flushes, checkpoint saves and the
        preemption check all move to the scan boundaries (every K
        steps); ``checkpoint_every`` rounds up to the next boundary.
        Resume composes: a checkpoint written at a non-K-aligned step
        fast-forwards per-batch and re-forms windows from there, and the
        per-step math is bitwise the K=1 path.  K=1 keeps today's
        per-step graphs and cadence exactly (kill-switch semantics like
        ``MXTPU_FUSED_STEP``).  The fused trainer path computes loss
        in-graph (no ``pred``): use Loss metrics there.  Eager
        ``gluon.Trainer`` loops cannot compile multi-step windows; K>1
        falls back to 1 with a warning.

        ``elastic_controller`` (``mx.elastic.ElasticController``, ISSUE
        8): the pause/resume hook for elastic membership.  At every
        step/window boundary — the exact seam the preemption check uses
        — a pending membership transition (worker death or join)
        pauses the loop, reshards params + optimizer state to the new
        dp in place (peer path), and resumes on the next batch with no
        cursor change.  When the reshard had to fall back to a
        CHECKPOINT (the peer transfer itself died), the restored state
        sits at an earlier step: the loop then stops cleanly with
        ``.preempted`` set — exactly the PR 4 preemption contract — and
        the caller re-enters ``fit(resume="auto")`` to replay from the
        restored cursor (bitwise, RNG included).

        ISSUE 13 extends the same seam: with a ``NoticeBoard`` attached
        to the controller, advance preemption notices drain doomed
        workers at the boundary (checkpoint-then-reshard — with a
        ``checkpoint_manager`` the loop wires the controller's
        ``drain_checkpoint`` to a sync save with the real cursor); a
        notice whose grace window already lapsed (typed
        ``DrainDeadline``) takes the emergency exit — sync checkpoint,
        stop with ``.preempted``.  ``autoscaler``
        (``mx.elastic.Autoscaler``): ticked once per boundary so
        load-based grow/shrink decisions land through the controller's
        epoch-fenced resync; inert under ``MXTPU_AUTOSCALE=0``.
        """
        import warnings
        from ... import checkpoint as ckpt_mod
        from ... import runtime as _runtime
        if epochs is None and batches is None:
            raise MXNetError("specify epochs or batches")
        fused = hasattr(self.trainer, "step_multi")
        k = int(steps_per_call) if steps_per_call is not None \
            else _runtime.steps_per_call()
        if k < 1:
            raise MXNetError("steps_per_call must be >= 1")
        if k > 1 and not fused:
            warnings.warn(
                "steps_per_call>1 needs a fused trainer with step_multi "
                "(parallel.DataParallelTrainer); the eager gluon.Trainer "
                "loop runs per-step — falling back to steps_per_call=1")
            k = 1
        start_epoch = skip_batches = 0
        self.preempted = False
        if resume is not None:
            start_epoch, skip_batches = self._resume(
                resume, checkpoint_manager)
        handlers = list(event_handlers or [])
        stopping = StoppingHandler(epochs, batches)
        handlers.append(stopping)
        handlers.append(MetricHandler(self.train_metrics))
        for h in handlers:
            if isinstance(h, TrainBegin):
                h.train_begin(self)
        # resume-aware stopping: epochs/batches count TOTAL training
        # progress, not progress-since-restart
        stopping.current_epoch = start_epoch
        stopping.current_batch = self.global_step
        self.stop_training = (
            (stopping.max_epoch is not None
             and start_epoch >= stopping.max_epoch)
            or (stopping.max_batch is not None
                and self.global_step >= stopping.max_batch))
        preempt = None
        if checkpoint_manager is not None:
            preempt = ckpt_mod.PreemptionHandler().install()
        try:
            while not self.stop_training:
                for h in handlers:
                    if isinstance(h, EpochBegin):
                        h.epoch_begin(self)
                batch_idx = 0
                epoch_done = True
                epoch_src, epoch_close = self._epoch_source(
                    train_data, prefetch_to_device, prefetch_depth)
                if elastic_controller is not None and \
                        checkpoint_manager is not None:
                    # checkpoint-THEN-reshard on notice-driven drains:
                    # the controller's drain saves through the SAME
                    # manager with the loop's real cursor (batch_idx is
                    # read at call time — the drain happens at a
                    # boundary inside run_window)
                    def _drain_save(step):
                        checkpoint_manager.save(
                            int(step), params=self.net,
                            trainer=self.trainer,
                            iterator={"epoch": self.current_epoch,
                                      "batch": batch_idx},
                            sync=True)
                    elastic_controller.drain_checkpoint = _drain_save

                def run_window(window):
                    """Execute a window of batches (ONE dispatch on the
                    fused K>1 path), then per-step bookkeeping and the
                    boundary-side checkpoint/preemption checks.  Window
                    size 1 reproduces the classic per-step cadence
                    exactly."""
                    nonlocal batch_idx
                    if fused:
                        pairs = [(b[0], b[1]) for b in window]
                        if len(pairs) == 1:
                            # K=1 / tail flush: today's per-step graph
                            results = [(None, self.trainer.step(*pairs[0]))]
                        else:
                            losses = self.trainer.step_multi(pairs)
                            results = [(None, losses[i])
                                       for i in range(len(pairs))]
                    else:
                        results = [self._train_step_eager(b[0], b[1])
                                   for b in window]
                    gs_before = self.global_step
                    for (pred, loss), b in zip(results, window):
                        self.global_step += 1
                        batch_idx += 1
                        for h in handlers:
                            if isinstance(h, BatchEnd):
                                h.batch_end(self, pred=pred, label=b[1],
                                            loss=loss)
                        if _watchdog.enabled() and loss is not None:
                            # the health watchdog's loss rules tick
                            # where the loss is ALREADY host-side
                            # (MetricHandler's update just pulled this
                            # same array) — no new device sync
                            _watchdog.on_step(
                                self.global_step,
                                loss=float(loss.asnumpy().mean()))  # mxlint: disable=HB10 -- MetricHandler.batch_end already synced this loss; re-reading the host buffer adds no dispatch
                    preempted = preempt is not None and \
                        preempt.check_step(self.global_step)
                    rewound = False
                    if elastic_controller is not None and not preempted:
                        from ...elastic.notices import DrainDeadline
                        try:
                            ev = elastic_controller.check_step(
                                self.global_step, trainer=self.trainer,
                                params=self.net)
                        except DrainDeadline:
                            # a notice's grace window lapsed before this
                            # boundary could drain it: emergency exit —
                            # the shared preemption save below is sync,
                            # then stop with .preempted (PR 4 contract)
                            ev = None
                            preempted = True
                        if ev is not None and \
                                ev.get("source") == "stop":
                            # degradation-ladder rung 3: capacity below
                            # the floor — checkpoint-and-stop now
                            preempted = True
                        if ev is not None and \
                                ev.get("source") == "checkpoint":
                            # the reshard recovered from a checkpoint at
                            # an EARLIER step: the in-memory cursor is
                            # now ahead of the state — stop cleanly
                            # (preemption semantics) so the caller
                            # re-enters fit(resume="auto") and replays
                            # from the restored cursor.  No save here:
                            # the restored checkpoint IS the durable
                            # state, and this loop's batch cursor no
                            # longer describes it.
                            self.global_step = ev["step"]
                            preempted = True
                            rewound = True
                    if autoscaler is not None and not preempted:
                        # the load-based control loop ticks at the same
                        # boundary; decisions apply through the
                        # controller's epoch-fenced resync at the NEXT
                        # boundary (no mid-window capacity change)
                        autoscaler.tick(step=self.global_step)
                    crossed = checkpoint_every and (
                        self.global_step // checkpoint_every
                        > gs_before // checkpoint_every)
                    if checkpoint_manager is not None and not rewound \
                            and (preempted or crossed):
                        # the in-flight window is DONE (scan boundary);
                        # a preemption save is synchronous — the process
                        # may be about to die and must not exit with a
                        # half-write
                        checkpoint_manager.save(
                            self.global_step, params=self.net,
                            trainer=self.trainer,
                            iterator={"epoch": self.current_epoch,
                                      "batch": batch_idx},
                            sync=preempted)
                    if preempted:
                        self.preempted = True
                        self.stop_training = True

                window = []
                for batch in epoch_src:
                    if skip_batches:
                        # fast-forward to the saved mid-epoch cursor
                        # (RNG was restored, so a deterministic pipeline
                        # replays the same batches)
                        skip_batches -= 1
                        batch_idx += 1
                        continue
                    window.append(batch)
                    if len(window) < k:
                        continue
                    run_window(window)
                    window = []
                    if self.stop_training:
                        epoch_done = not self.preempted
                        break
                if window and not self.stop_training:
                    # tail: the epoch length was not a multiple of K —
                    # flush the partial window (same per-step math)
                    run_window(window)
                    if self.stop_training:
                        epoch_done = not self.preempted
                if epoch_close is not None:
                    epoch_close()   # join the prefetch worker (idempotent)
                if self.preempted:
                    break           # mid-epoch: no epoch_end bookkeeping
                for h in handlers:
                    if isinstance(h, EpochEnd):
                        h.epoch_end(self)
                self.current_epoch += 1
                if epoch_done and checkpoint_manager is not None and \
                        checkpoint_every is None:
                    # default cadence: one checkpoint per finished epoch
                    checkpoint_manager.save(
                        self.global_step, params=self.net,
                        trainer=self.trainer,
                        iterator={"epoch": self.current_epoch,
                                  "batch": 0})
            for h in handlers:
                if isinstance(h, TrainEnd):
                    h.train_end(self)
        finally:
            if preempt is not None:
                preempt.uninstall()
            if checkpoint_manager is not None:
                checkpoint_manager.wait_until_finished()
