"""``gluon.contrib.rnn`` — experimental recurrent cells.

Reference: python/mxnet/gluon/contrib/rnn/{rnn_cell.py,conv_rnn_cell.py}
(SURVEY.md §2.2 "Gluon contrib"): VariationalDropoutCell (one dropout mask
reused across all time steps — Gal & Ghahramani) and convolutional LSTM
cells (gates are convolutions over spatial state).
"""
from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock
from ..rnn.rnn_cell import HybridRecurrentCell, LSTMCell

__all__ = ["VariationalDropoutCell", "Conv2DLSTMCell"]


class VariationalDropoutCell(HybridRecurrentCell):
    """Wrap a cell; apply the SAME dropout mask at every step.

    Reference: contrib.rnn.VariationalDropoutCell — masks are drawn once
    per sequence (on first step after reset) for inputs, states, outputs.
    """

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0, **kwargs):
        super().__init__(**kwargs)
        self.base_cell = base_cell
        self._drop_inputs = drop_inputs
        self._drop_states = drop_states
        self._drop_outputs = drop_outputs
        self._mask_inputs = None
        self._mask_states = None
        self._mask_outputs = None

    def reset(self):
        super().reset()
        # RecurrentCell.__init__ calls reset() before base_cell is assigned
        if getattr(self, "base_cell", None) is not None:
            self.base_cell.reset()
        self._mask_inputs = None
        self._mask_states = None
        self._mask_outputs = None

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        return self.base_cell.begin_state(batch_size=batch_size, **kwargs)

    def infer_shape(self, x, *args):
        if hasattr(self.base_cell, "infer_shape"):
            self.base_cell.infer_shape(x, *args)

    def _mask(self, p, like):
        import jax.numpy as jnp
        from ...ndarray import random as _rnd
        from ...ndarray.ndarray import NDArray, apply_nary
        import jax
        key = _rnd.next_key()

        def fn(d):
            keep = jax.random.bernoulli(key, 1.0 - p, d.shape)
            return keep.astype(d.dtype) / (1.0 - p)

        return apply_nary(fn, [like], name="vd_mask")

    def __call__(self, inputs, states):
        from ... import _tape
        if _tape.is_training():
            if self._drop_inputs and self._mask_inputs is None:
                self._mask_inputs = self._mask(self._drop_inputs, inputs)
            if self._drop_states and self._mask_states is None:
                self._mask_states = self._mask(self._drop_states, states[0])
        if self._mask_inputs is not None:
            inputs = inputs * self._mask_inputs
        if self._mask_states is not None:
            states = [states[0] * self._mask_states] + list(states[1:])
        out, nstates = self.base_cell(inputs, states)
        if _tape.is_training() and self._drop_outputs:
            if self._mask_outputs is None:
                self._mask_outputs = self._mask(self._drop_outputs, out)
            out = out * self._mask_outputs
        return out, nstates

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        return super().unroll(length, inputs, begin_state, layout,
                              merge_outputs)


class Conv2DLSTMCell(HybridRecurrentCell):
    """Convolutional LSTM (xingjian et al.): gates are 2D convolutions.

    Reference: contrib.rnn.Conv2DLSTMCell. input/state: (B, C, H, W);
    hidden state has `hidden_channels` channels at the same spatial size
    (same-padding convs).
    """

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), **kwargs):
        super().__init__(**kwargs)
        self._input_shape = tuple(input_shape)    # (C, H, W)
        self._hc = hidden_channels
        pad_i = tuple(k // 2 for k in i2h_kernel)
        pad_h = tuple(k // 2 for k in h2h_kernel)
        with self.name_scope():
            self.i2h = nn.Conv2D(4 * hidden_channels, i2h_kernel,
                                 padding=pad_i,
                                 in_channels=self._input_shape[0])
            self.h2h = nn.Conv2D(4 * hidden_channels, h2h_kernel,
                                 padding=pad_h, use_bias=False,
                                 in_channels=hidden_channels)

    def state_info(self, batch_size=0):
        c, h, w = self._input_shape
        shape = (batch_size, self._hc, h, w)
        return [{"shape": shape, "__layout__": "NCHW"},
                {"shape": shape, "__layout__": "NCHW"}]

    def _alias(self):
        return "conv_lstm"

    def __call__(self, inputs, states):
        import jax
        from ...ndarray.ndarray import apply_nary
        gates = self.i2h(inputs) + self.h2h(states[0])

        def fn(g, c_prev):
            i, f, c_in, o = [g[:, k * self._hc:(k + 1) * self._hc]
                             for k in range(4)]
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            o = jax.nn.sigmoid(o)
            c = f * c_prev + i * jax.numpy.tanh(c_in)
            return jax.numpy.tanh(c) * o, c

        out, c = apply_nary(fn, [gates, states[1]], n_out=2,
                            name="conv_lstm_step")
        return out, [out, c]
