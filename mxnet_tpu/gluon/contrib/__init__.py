"""``gluon.contrib`` (reference: python/mxnet/gluon/contrib/)."""
from . import nn
from . import estimator
from . import rnn
from . import data
