"""``gluon.contrib.nn`` — notably SyncBatchNorm.

Reference: python/mxnet/gluon/contrib/nn/basic_layers.py. SyncBatchNorm is
Hang Zhang's cross-device BN (SURVEY.md §2.2 "Gluon contrib"): the reference
synchronized batch statistics across GPUs through the KVStore/comm layer.
TPU-native: when the batch is sharded over a mesh 'dp' axis inside a jitted
step, jnp.mean over the batch axis IS the cross-replica mean (XLA lowers it
to a psum over the shards) — so SyncBatchNorm falls out of the sharding
algebra. The class remains for API parity and for the eager path.
"""
from __future__ import annotations

from ..nn.basic_layers import BatchNorm
from ..block import HybridBlock
from .. import nn as _nn

__all__ = ["SyncBatchNorm", "Identity", "Concurrent", "HybridConcurrent"]


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm.

    With a sharded batch inside jit/DataParallelTrainer the statistics are
    global automatically; num_devices is accepted for API compatibility."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x


class Concurrent(_nn.Sequential):
    """Parallel branches concatenated along `axis` (reference
    contrib.nn.Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as nd
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class HybridConcurrent(_nn.HybridSequential):
    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)
