"""``gluon.data`` (reference: python/mxnet/gluon/data/)."""
from .dataset import Dataset, SimpleDataset, ArrayDataset, RecordFileDataset
from .dataloader import (DataLoader, default_batchify_fn, Sampler,
                         SequentialSampler, RandomSampler, BatchSampler,
                         FilterSampler)
from . import vision
