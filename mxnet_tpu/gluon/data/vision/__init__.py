"""``gluon.data.vision`` (reference: python/mxnet/gluon/data/vision/)."""
from .datasets import *  # noqa: F401,F403
from . import transforms
