"""Vision datasets: MNIST / FashionMNIST / CIFAR10/100 / ImageRecordDataset.

Reference: python/mxnet/gluon/data/vision/datasets.py. Downloads are
unavailable (no egress): datasets read from local files in the standard
formats, or generate deterministic synthetic data when
``synthetic=True``/MXTPU_SYNTHETIC_DATA=1 — used by tests and benchmarks
(same role as tests/python/train synthetic paths in the reference).
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as _np

from ....base import MXNetError
from ....ndarray.ndarray import array, NDArray
from ..dataset import Dataset, ArrayDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _synthetic_enabled(flag):
    return flag or os.environ.get("MXTPU_SYNTHETIC_DATA", "0") == "1"


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx-format files (train-images-idx3-ubyte[.gz] etc.)
    or synthetic digits when unavailable."""

    _shape = (28, 28, 1)
    _nclass = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None, synthetic=False, size=None):
        self._train = train
        self._synthetic = _synthetic_enabled(synthetic)
        self._size = size
        super().__init__(root, transform)

    def _file_names(self):
        if self._train:
            return "train-images-idx3-ubyte", "train-labels-idx1-ubyte"
        return "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"

    def _get_data(self):
        img_name, lbl_name = self._file_names()
        img_path = os.path.join(self._root, img_name)
        lbl_path = os.path.join(self._root, lbl_name)
        if not self._synthetic and (
                os.path.exists(img_path) or os.path.exists(img_path + ".gz")):
            self._data, self._label = _read_idx(img_path, lbl_path)
        else:
            n = self._size or (6000 if self._train else 1000)
            self._data, self._label = _synthetic_digits(n, self._shape,
                                                        self._nclass,
                                                        seed=1 if self._train
                                                        else 2)
        self._data = array(self._data.astype("float32") / 255.0
                           if self._data.dtype == _np.uint8
                           else self._data, dtype="float32")
        # keep uint8-style HWC uint8 semantics? reference returns uint8 HWC;
        # transforms.ToTensor does the scaling. We return float [0,1] HWC
        # scaled only if no transform provided handles it — match reference:
        self._label = self._label.astype("int32")

    def __getitem__(self, idx):
        data = self._data[idx]
        label = self._label[idx]
        if self._transform is not None:
            return self._transform(data, label)
        return data, label


def _read_idx(img_path, lbl_path):
    def opener(p):
        if os.path.exists(p):
            return open(p, "rb")
        return gzip.open(p + ".gz", "rb")
    with opener(lbl_path) as f:
        magic, num = struct.unpack(">II", f.read(8))
        label = _np.frombuffer(f.read(), dtype=_np.uint8).astype(_np.int32)
    with opener(img_path) as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        data = _np.frombuffer(f.read(), dtype=_np.uint8).reshape(
            num, rows, cols, 1)
    return data, label


def _synthetic_digits(n, shape, nclass, seed=0):
    """Deterministic class-separable synthetic data with SPATIALLY SMOOTH
    per-class templates (low-frequency patterns upsampled from a coarse
    grid), so conv+pool architectures can learn it like real digits — iid
    noise templates would be adversarial for convnets."""
    rng = _np.random.RandomState(seed)
    h, w = shape[0], shape[1]
    c = shape[2] if len(shape) > 2 else 1
    coarse = _np.random.RandomState(42).uniform(
        0, 1, (nclass, 5, 5, c)).astype("float32")
    # bilinear upsample 5x5 -> HxW per class
    ys = _np.linspace(0, 4, h)
    xs = _np.linspace(0, 4, w)
    y0 = _np.floor(ys).astype(int)
    x0 = _np.floor(xs).astype(int)
    y1 = _np.minimum(y0 + 1, 4)
    x1 = _np.minimum(x0 + 1, 4)
    wy = (ys - y0)[None, :, None, None]
    wx = (xs - x0)[None, None, :, None]
    templates = (coarse[:, y0][:, :, x0] * (1 - wy) * (1 - wx) +
                 coarse[:, y1][:, :, x0] * wy * (1 - wx) +
                 coarse[:, y0][:, :, x1] * (1 - wy) * wx +
                 coarse[:, y1][:, :, x1] * wy * wx)
    labels = rng.randint(0, nclass, n).astype("int32")
    noise = rng.uniform(0, 0.25, (n,) + tuple(shape)).astype("float32")
    data = templates[labels].reshape((-1,) + tuple(shape)) * 0.75 + noise
    return (_np.clip(data, 0, 1) * 255).astype(_np.uint8), labels


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None, synthetic=False, size=None):
        super().__init__(root, train, transform, synthetic, size)


class CIFAR10(_DownloadedDataset):
    _shape = (32, 32, 3)
    _nclass = 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None, synthetic=False, size=None):
        self._train = train
        self._synthetic = _synthetic_enabled(synthetic)
        self._size = size
        super().__init__(root, transform)

    def _get_data(self):
        files = [f"data_batch_{i}.bin" for i in range(1, 6)] if self._train \
            else ["test_batch.bin"]
        paths = [os.path.join(self._root, f) for f in files]
        if not self._synthetic and all(os.path.exists(p) for p in paths):
            data, label = [], []
            for p in paths:
                raw = _np.fromfile(p, dtype=_np.uint8).reshape(-1, 3073)
                label.append(raw[:, 0])
                data.append(raw[:, 1:].reshape(-1, 3, 32, 32)
                            .transpose(0, 2, 3, 1))
            self._data = _np.concatenate(data)
            self._label = _np.concatenate(label).astype("int32")
        else:
            n = self._size or (5000 if self._train else 1000)
            self._data, self._label = _synthetic_digits(
                n, self._shape, self._nclass, seed=3 if self._train else 4)
        self._data = array(self._data.astype("float32") / 255.0,
                           dtype="float32")
        self._label = self._label.astype("int32")

    def __getitem__(self, idx):
        data = self._data[idx]
        label = self._label[idx]
        if self._transform is not None:
            return self._transform(data, label)
        return data, label


class CIFAR100(CIFAR10):
    _nclass = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None,
                 synthetic=False, size=None):
        self._fine_label = fine_label
        super().__init__(root, train, transform, synthetic, size)


class ImageRecordDataset(Dataset):
    """Images + labels from a RecordIO file (reference
    vision.ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        from .... import recordio, image
        self._rec = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio, image
        record = self._rec[idx]
        header, img = recordio.unpack(record)
        data = image.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(data, label)
        return data, label

    def __len__(self):
        return len(self._rec)


class ImageFolderDataset(Dataset):
    """folder/class_x/*.jpg layout (reference vision.ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from .... import image
        with open(self.items[idx][0], "rb") as f:
            img = image.imdecode(f.read(), self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
