"""``gluon.data.vision.transforms`` — image transforms as (Hybrid)Blocks.

Reference: python/mxnet/gluon/data/vision/transforms.py (ToTensor, Normalize,
Resize, CenterCrop, RandomResizedCrop, RandomFlipLeftRight, Cast, Compose).
Pixel transforms run on host numpy (the input pipeline side of the fence);
normalization also works on device arrays.
"""
from __future__ import annotations

import numpy as _np

from ....base import MXNetError
from ....ndarray.ndarray import NDArray, array
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomResizedCrop", "RandomFlipLeftRight", "RandomFlipTopBottom",
           "RandomCrop", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting"]


def _to_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()  # mxlint: disable=HB02 -- host-side eager Block
    return _np.asarray(x)


class Compose(Sequential):
    """Sequentially composes transforms. Reference: transforms.Compose."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(Block):
    """HWC uint8/float [0,255 or 0,1] -> CHW float32 [0,1].
    Reference: transforms.ToTensor."""

    def forward(self, x):
        np_x = _to_np(x).astype("float32")
        if np_x.max() > 1.5:  # mxlint: disable=HB01 -- host numpy, not a tracer
            np_x = np_x / 255.0
        if np_x.ndim == 3:
            np_x = np_x.transpose(2, 0, 1)
        elif np_x.ndim == 4:
            np_x = np_x.transpose(0, 3, 1, 2)
        return array(np_x)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = _np.asarray(mean, dtype="float32")
        self._std = _np.asarray(std, dtype="float32")

    def forward(self, x):
        np_x = _to_np(x).astype("float32")
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return array((np_x - mean) / std)


def _resize_np(img, size):
    """Bilinear resize in numpy (no cv2 dependency guarantee)."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        ow, oh = size, size
    else:
        ow, oh = size
    ys = _np.linspace(0, h - 1, oh)
    xs = _np.linspace(0, w - 1, ow)
    y0 = _np.floor(ys).astype(int)
    x0 = _np.floor(xs).astype(int)
    y1 = _np.minimum(y0 + 1, h - 1)
    x1 = _np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    img = img.astype("float32")
    out = (img[_np.ix_(y0, x0)] * (1 - wy) * (1 - wx) +
           img[_np.ix_(y1, x0)] * wy * (1 - wx) +
           img[_np.ix_(y0, x1)] * (1 - wy) * wx +
           img[_np.ix_(y1, x1)] * wy * wx)
    return out


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio

    def forward(self, x):
        return array(_resize_np(_to_np(x), self._size))


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        np_x = _to_np(x)
        h, w = np_x.shape[:2]
        cw, ch = self._size
        x0 = max((w - cw) // 2, 0)
        y0 = max((h - ch) // 2, 0)
        return array(np_x[y0:y0 + ch, x0:x0 + cw])


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad

    def forward(self, x):
        np_x = _to_np(x)
        if self._pad:
            np_x = _np.pad(np_x, ((self._pad, self._pad),
                                  (self._pad, self._pad), (0, 0)),
                           mode="constant")
        h, w = np_x.shape[:2]
        cw, ch = self._size
        x0 = _np.random.randint(0, max(w - cw, 0) + 1)  # mxlint: disable=HB05 -- host-side eager Block
        y0 = _np.random.randint(0, max(h - ch, 0) + 1)  # mxlint: disable=HB05 -- host-side eager Block
        return array(np_x[y0:y0 + ch, x0:x0 + cw])  # mxlint: disable=HB03 -- host-side eager Block


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        np_x = _to_np(x)
        h, w = np_x.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _np.random.uniform(*self._scale) * area  # mxlint: disable=HB05 -- host-side eager Block
            aspect = _np.random.uniform(*self._ratio)  # mxlint: disable=HB05 -- host-side eager Block
            cw = int(round(_np.sqrt(target_area * aspect)))
            ch = int(round(_np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:  # mxlint: disable=HB01 -- host-side eager Block
                x0 = _np.random.randint(0, w - cw + 1)  # mxlint: disable=HB05 -- host-side eager Block
                y0 = _np.random.randint(0, h - ch + 1)  # mxlint: disable=HB05 -- host-side eager Block
                crop = np_x[y0:y0 + ch, x0:x0 + cw]  # mxlint: disable=HB03 -- host-side eager Block
                return array(_resize_np(crop, self._size))
        return array(_resize_np(np_x, self._size))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        np_x = _to_np(x)
        if _np.random.rand() < 0.5:  # mxlint: disable=HB01,HB05 -- host-side eager Block
            np_x = np_x[:, ::-1].copy()
        return array(np_x)


class RandomFlipTopBottom(Block):
    def forward(self, x):
        np_x = _to_np(x)
        if _np.random.rand() < 0.5:  # mxlint: disable=HB01,HB05 -- host-side eager Block
            np_x = np_x[::-1].copy()
        return array(np_x)


class RandomBrightness(Block):
    """Scale all channels by U(1-b, 1+b) (reference transforms.RandomBrightness)."""

    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        np_x = _to_np(x).astype(_np.float32)
        alpha = 1.0 + _np.random.uniform(-self._b, self._b)  # mxlint: disable=HB05 -- host-side eager Block
        return array(np_x * alpha)


_GRAY_COEF = _np.array([0.299, 0.587, 0.114], _np.float32)
_T_YIQ = _np.array([[0.299, 0.587, 0.114],
                    [0.596, -0.274, -0.321],
                    [0.211, -0.523, 0.311]], _np.float32)
_T_RGB = _np.array([[1.0, 0.956, 0.621],
                    [1.0, -0.272, -0.647],
                    [1.0, -1.107, 1.705]], _np.float32)


class RandomContrast(Block):
    """Blend with the per-image gray mean (reference RandomContrast)."""

    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        np_x = _to_np(x).astype(_np.float32)
        alpha = 1.0 + _np.random.uniform(-self._c, self._c)  # mxlint: disable=HB05 -- host-side eager Block
        # reference blends with the LUMINANCE mean (image.random_contrast),
        # not the unweighted channel mean
        gray = (np_x * _GRAY_COEF).sum(axis=-1).mean()
        return array(np_x * alpha + gray * (1.0 - alpha))


class RandomSaturation(Block):
    """Blend with the per-pixel gray value (reference RandomSaturation)."""

    def __init__(self, saturation):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        np_x = _to_np(x).astype(_np.float32)
        alpha = 1.0 + _np.random.uniform(-self._s, self._s)  # mxlint: disable=HB05 -- host-side eager Block
        gray = (np_x * _GRAY_COEF).sum(axis=-1, keepdims=True)
        return array(np_x * alpha + gray * (1.0 - alpha))


class RandomHue(Block):
    """Rotate the hue via the YIQ transform (reference RandomHue)."""

    def __init__(self, hue):
        super().__init__()
        self._h = hue

    def forward(self, x):
        np_x = _to_np(x).astype(_np.float32)
        alpha = _np.random.uniform(-self._h, self._h) * _np.pi  # mxlint: disable=HB05 -- host-side eager Block
        u, w = _np.cos(alpha), _np.sin(alpha)
        rot = _np.array([[1.0, 0.0, 0.0],
                         [0.0, u, -w],
                         [0.0, w, u]], _np.float32)
        m = _T_RGB @ rot @ _T_YIQ
        return array(np_x @ m.T)


class RandomColorJitter(Block):
    """Apply brightness/contrast/saturation/hue jitter in random order
    (reference RandomColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        order = _np.random.permutation(len(self._ts))  # mxlint: disable=HB05 -- host-side eager Block
        for i in order:
            x = self._ts[int(i)](x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (reference RandomLighting)."""

    _EIGVAL = _np.array([55.46, 4.794, 1.148], _np.float32)
    _EIGVEC = _np.array([[-0.5675, 0.7192, 0.4009],
                         [-0.5808, -0.0045, -0.8140],
                         [-0.5836, -0.6948, 0.4203]], _np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        np_x = _to_np(x).astype(_np.float32)
        a = _np.random.normal(0, self._alpha, size=(3,)).astype(_np.float32)  # mxlint: disable=HB05 -- host-side eager Block
        rgb = (self._EIGVEC * a * self._EIGVAL).sum(axis=1)
        return array(np_x + rgb)
