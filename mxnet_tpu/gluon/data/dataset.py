"""Gluon datasets.

Reference: python/mxnet/gluon/data/dataset.py (Dataset, SimpleDataset,
ArrayDataset, RecordFileDataset) — SURVEY.md §2.2 "Gluon data".
"""
from __future__ import annotations

import os

from ...base import MXNetError
from ...ndarray.ndarray import NDArray

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def shard(self, num_shards, index):
        assert 0 <= index < num_shards
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        return SimpleDataset([self[i] for i in range(start, end)])

    def take(self, count):
        return SimpleDataset([self[i] for i in range(min(count, len(self)))])

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zips one or more array-likes. Reference: data.ArrayDataset."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                f"All arrays must have the same length; {len(data)} != " \
                f"{self._length} at position {i}"
            if isinstance(data, NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(data[idx] for data in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO (.rec) file.
    Reference: data.RecordFileDataset over MXIndexedRecordIO."""

    def __init__(self, filename):
        from ... import recordio
        from ...utils import native
        self._filename = filename
        self._native = None
        if native.available():
            # C++ mmap reader builds its own index at open (src/recordio.cc)
            self._native = native.NativeRecordFile(filename)
            self._record = None
            return
        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")
        if not self._record.keys:
            # no .idx sidecar: build the index with one sequential scan
            pos = self._record.tell()
            while self._record.read() is not None:
                self._record.idx[len(self._record.keys)] = pos
                self._record.keys.append(len(self._record.keys))
                pos = self._record.tell()

    def __getitem__(self, idx):
        if self._native is not None:
            return self._native[idx]
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        if self._native is not None:
            return len(self._native)
        return len(self._record.keys)
