"""Gluon ``DataLoader`` + batchify + samplers.

Reference: python/mxnet/gluon/data/dataloader.py and sampler.py.

TPU-native notes: the reference forked worker *processes* and moved batches
through shared-memory NDArrays (with engine fork handlers, SURVEY.md §5.2).
Here batching produces host numpy and a single ``jax.device_put`` ships the
batch to the TPU — the XLA transfer engine overlaps it with compute, which is
the role PrefetcherIter played. Thread-based workers cover the
decode-bound case (JPEG decode releases the GIL in PIL/cv2); the native C++
recordio reader (src/) covers the IO-bound case.
"""
from __future__ import annotations

import os
import threading
import queue as _queue

import numpy as _np

from ...base import MXNetError
from ...ndarray.ndarray import NDArray, array

__all__ = ["DataLoader", "default_batchify_fn", "Sampler", "SequentialSampler",
           "RandomSampler", "BatchSampler", "FilterSampler"]


# ----------------------------------------------------------------------
# samplers (reference: gluon/data/sampler.py)
# ----------------------------------------------------------------------

class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        indices = _np.arange(self._length)
        _np.random.shuffle(indices)
        return iter(indices.tolist())

    def __len__(self):
        return self._length


class FilterSampler(Sampler):
    """Samples indices whose dataset element satisfies fn (reference
    gluon/data/sampler.py FilterSampler)."""

    def __init__(self, fn, dataset):
        self._indices = [i for i in range(len(dataset)) if fn(dataset[i])]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class BatchSampler(Sampler):
    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise MXNetError(
                    f"last_batch must be keep/discard/rollover, got "
                    f"{self._last_batch}")

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) // \
                self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        if self._last_batch == "rollover":
            return (len(self._prev) + len(self._sampler)) // self._batch_size
        raise MXNetError(f"bad last_batch {self._last_batch}")


# ----------------------------------------------------------------------
# batchify
# ----------------------------------------------------------------------

def default_batchify_fn(data):
    """Stack samples into a batch (reference dataloader.default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp
        return NDArray(jnp.stack([d.data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    return array(data, dtype=data.dtype if data.dtype != _np.float64
                 else "float32")


def _thread_worker_fn(samples, batchify_fn, dataset):
    return batchify_fn([dataset[i] for i in samples])


# ----------------------------------------------------------------------
# process workers (reference default: DataLoader forks worker processes;
# here they are SPAWNED so each worker builds its own fresh CPU-only jax
# — a forked child would inherit the parent's initialized XLA client
# whose threads do not survive fork, and must never race the parent for
# the accelerator)
# ----------------------------------------------------------------------

_MP_DATASET = None
_MP_BATCHIFY = None


def _load_cpu_pinned(payload_bytes):
    """Unpickle target of _CpuPinnedPayload: pins this process to CPU jax
    BEFORE the inner payload (which may contain NDArrays that initialize
    a backend on unpickle) is touched.  Because the pin rides inside the
    pickle itself, it holds no matter when or how the worker was spawned
    — including Pool's respawn of a dead worker, where no parent-side env
    juggling could be in effect.

    The env var alone is NOT enough on accelerator hosts: a sitecustomize
    may have force-registered the accelerator plugin at interpreter start,
    and backend discovery initializes every REGISTERED plugin even under
    JAX_PLATFORMS=cpu — on a wedged tunnel that hangs the worker at batch
    0.  So this replicates the full force_cpu treatment (_cpu_defense.py):
    scrub the sitecustomize path, pop non-cpu backend factories, and pin
    the already-imported jax config."""
    import os
    import pickle
    import sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    # Mirrors repo-root _cpu_defense.force_cpu — keep the two in sync.
    # It cannot be imported here: the repo-root module is not on a spawned
    # worker's path, and a package-internal copy would run
    # mxnet_tpu/__init__ (-> jax) before the pin, defeating it.
    if "jax" in sys.modules:   # plugin already registered: env pin too late
        try:
            from jax._src import xla_bridge as _xb
            for _name in list(getattr(_xb, "_backend_factories", {})):
                if _name not in ("cpu", "interpreter"):
                    _xb._backend_factories.pop(_name, None)
        except Exception:
            pass
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    return pickle.loads(payload_bytes)


class _CpuPinnedPayload:
    """Wraps an object so that UNPICKLING it first pins the process to
    CPU jax.  Unpickles to the wrapped object itself, not the wrapper."""

    def __init__(self, obj):
        import pickle
        self._payload = pickle.dumps(obj)

    def __reduce__(self):
        return (_load_cpu_pinned, (self._payload,))


def _mp_worker_init(dataset, batchify_fn):
    # the real cpu pin already happened while unpickling the
    # _CpuPinnedPayload initargs; keep the global wiring only
    global _MP_DATASET, _MP_BATCHIFY
    _MP_DATASET = dataset
    _MP_BATCHIFY = batchify_fn


def _map_structure(fn, item):
    """Map leaves through fn preserving list/tuple/namedtuple structure."""
    if isinstance(item, (list, tuple)):
        mapped = [_map_structure(fn, i) for i in item]
        if hasattr(item, "_fields"):      # namedtuple
            return type(item)(*mapped)
        return type(item)(mapped)
    return fn(item)


def _to_host(item):
    """NDArray -> numpy for the pickle trip back to the parent."""
    return _map_structure(
        lambda x: x.asnumpy() if isinstance(x, NDArray) else x, item)


def _from_host(item):
    return _map_structure(
        lambda x: array(x) if isinstance(x, _np.ndarray) else x, item)


def _mp_worker_fn(samples):
    return _to_host(_MP_BATCHIFY([_MP_DATASET[i] for i in samples]))


class DataLoader:
    """Loads data from a Dataset and returns mini-batches.

    Reference: gluon.data.DataLoader (num_workers worker processes,
    thread_pool=False default). Deliberate TPU-first deviation: OUR
    default is ``thread_pool=True`` — device arrays are process-local
    under jax, GIL-releasing C++ decode (src/image_decode.cc) scales in
    threads, and thread workers can hold NDArray datasets/transforms
    directly. ``thread_pool=False`` opts into true worker PROCESSES
    (reference semantics) for host-only pipelines: the dataset and
    batchify_fn must pickle, workers are spawned with a fresh CPU-only
    jax (never the parent's accelerator), and batches return as numpy.
    ``num_workers=0`` means synchronous.

    ``prefetch_to_device=True`` chains an ``io.DevicePrefetcher`` after
    batching: a worker thread ships batch N+1 to the device (sharded
    over an active ``parallel`` mesh) while the training step consumes
    batch N — see docs/INPUT_PIPELINE.md.  ``prefetch_depth=`` sets how
    many batches the device stage reads ahead (default:
    ``MXTPU_PREFETCH_DEPTH`` env, else 2 — double buffering).
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120,
                 prefetch_to_device=False, prefetch_depth=None):
        self._dataset = dataset
        self._timeout = timeout
        self._prefetch_to_device = prefetch_to_device
        # device-stage read-ahead depth (batches staged on device beyond
        # the one being consumed); None -> MXTPU_PREFETCH_DEPTH, default 2
        self._prefetch_depth = prefetch_depth
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle else \
                    SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise MXNetError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._thread_pool = thread_pool
        self._mp_pool = None

    def __iter__(self):
        if self._prefetch_to_device:
            # overlap H2D with consumer compute: batches arrive already
            # device-resident (sharded over an active parallel mesh) —
            # see io.DevicePrefetcher / docs/INPUT_PIPELINE.md
            from ...io import DevicePrefetcher
            pf = DevicePrefetcher(self._host_iter(),
                                  depth=self._prefetch_depth)
            try:
                yield from pf
            finally:
                pf.close()
        else:
            yield from self._host_iter()

    def _host_iter(self):
        from ... import debug as _debug
        if self._num_workers == 0 or _debug.determinism_enabled():
            # MXTPU_ENFORCE_DETERMINISM: random transforms draw from the
            # global numpy RNG; worker-thread interleaving would reorder the
            # draws, so the pipeline runs synchronously (throughput for
            # reproducibility, like the reference's ENFORCE_DETERMINISM
            # rejecting fast non-deterministic cuDNN algos)
            for batch in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch])
            return
        if self._thread_pool:
            yield from self._threaded_iter()
        else:
            # reference default: worker processes (dataset + batchify must
            # pickle; results come back as numpy and re-materialize here)
            yield from self._process_iter()

    def _ensure_mp_pool(self):
        if self._mp_pool is None:
            import multiprocessing as mp
            ctx = mp.get_context("spawn")
            # Two-layer CPU pin for the spawned workers:
            #  1. HERE, around the spawn: JAX_PLATFORMS=cpu in the
            #     parent's os.environ and the accelerator sitecustomize
            #     scrubbed from PYTHONPATH.  Children inherit the env at
            #     exec — BEFORE their sitecustomize could import jax and
            #     register the accelerator plugin (a registered plugin
            #     initializes even under JAX_PLATFORMS=cpu and must never
            #     race the parent for the chip).
            #  2. Inside the initargs pickle (_CpuPinnedPayload), which
            #     re-applies the full pin at unpickle time — covers Pool's
            #     respawn of a dead worker, where (1) is long restored.
            saved = {k: os.environ.get(k)
                     for k in ("JAX_PLATFORMS", "PYTHONPATH")}
            os.environ["JAX_PLATFORMS"] = "cpu"
            pp = os.environ.get("PYTHONPATH", "")
            os.environ["PYTHONPATH"] = os.pathsep.join(
                p for p in pp.split(os.pathsep) if ".axon_site" not in p)
            try:
                self._mp_pool = ctx.Pool(
                    self._num_workers, initializer=_mp_worker_init,
                    initargs=(_CpuPinnedPayload(self._dataset),
                              _CpuPinnedPayload(self._batchify_fn)))
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
        return self._mp_pool

    def _process_iter(self):
        try:
            pool = self._ensure_mp_pool()
        except Exception as e:   # unpicklable dataset/transform etc.
            raise MXNetError(
                f"DataLoader process workers failed to start ({e}); pass "
                f"thread_pool=True for in-process workers (required when "
                f"the dataset or transforms are not picklable)") from e
        batches = list(self._batch_sampler)
        depth = max(self._prefetch, self._num_workers, 1)
        pending = {}
        nxt = 0
        for want in range(len(batches)):
            while nxt < len(batches) and len(pending) < depth:
                pending[nxt] = pool.apply_async(_mp_worker_fn,
                                                (batches[nxt],))
                nxt += 1
            try:
                item = pending.pop(want).get(timeout=self._timeout)
            except Exception as e:
                if "Timeout" in type(e).__name__:
                    raise MXNetError(
                        f"DataLoader worker timed out after "
                        f"{self._timeout}s waiting for batch {want}")
                raise
            yield _from_host(item)

    def __del__(self):
        pool = getattr(self, "_mp_pool", None)
        if pool is not None:
            try:
                pool.terminate()
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass

    def _threaded_iter(self):
        batches = list(self._batch_sampler)
        stop = threading.Event()
        # permits bound decoded-but-unconsumed batches (prefetch depth)
        sem = threading.Semaphore(max(self._prefetch, self._num_workers, 1))
        in_q = _queue.SimpleQueue()
        for item in enumerate(batches):
            in_q.put(item)
        results = _queue.SimpleQueue()

        def worker():
            while not stop.is_set():
                if not sem.acquire(timeout=0.1):
                    continue
                try:
                    idx, samples = in_q.get_nowait()
                except _queue.Empty:
                    sem.release()
                    return
                try:
                    results.put((idx, self._batchify_fn(
                        [self._dataset[i] for i in samples])))
                except Exception as e:  # propagate to consumer
                    results.put((idx, e))

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        buffered = {}
        try:
            for want in range(len(batches)):
                while want not in buffered:
                    try:
                        idx, item = results.get(timeout=self._timeout)
                    except _queue.Empty:
                        raise MXNetError(
                            f"DataLoader worker timed out after "
                            f"{self._timeout}s waiting for batch {want}")
                    buffered[idx] = item
                item = buffered.pop(want)
                sem.release()
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            # unblocks workers even if iteration is abandoned mid-epoch
            stop.set()

    def __len__(self):
        return len(self._batch_sampler)
