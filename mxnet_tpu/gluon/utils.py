"""Gluon utilities: ``split_and_load``, ``split_data``, ``clip_global_norm``.

Reference: python/mxnet/gluon/utils.py. On TPU, ``split_and_load`` over a list
of contexts maps to sharding one batch across devices; the single-`Context`
call keeps the reference's list-of-slices contract so existing multi-device
training loops run unchanged.
"""
from __future__ import annotations

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import Context
from ..ndarray.ndarray import NDArray, array

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}.")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Reference: gluon.utils.split_and_load — slice batch across contexts."""
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the concatenated L2 norm is at most max_norm.
    Reference: gluon.utils.clip_global_norm."""
    if not arrays:
        raise MXNetError("arrays must not be empty")
    total = jnp.sqrt(sum(jnp.sum(jnp.square(a.data)) for a in arrays))
    total_f = float(total)
    if check_isfinite and not _np.isfinite(total_f):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (total_f + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._set_data(a.data * scale)
    return total_f


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    """Kept for API parity; this build environment has no egress."""
    raise MXNetError(
        "download() is unavailable: this environment has no network access. "
        "Place files locally and pass a path instead.")
