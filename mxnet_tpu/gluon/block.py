"""Gluon ``Block`` / ``HybridBlock`` / ``SymbolBlock`` and the TPU CachedOp.

Reference: python/mxnet/gluon/block.py + src/imperative/cached_op.cc
(SURVEY.md §2.1 "CachedOp" — "the crown jewel mapping").

The mapping implemented here:

  reference                         TPU rebuild
  ---------                         -----------
  hybridize()                       mark block active; build CachedOp
  CachedOp trace (nnvm graph)       jax.jit trace of the block's forward
  static_alloc/static_shape         XLA static shapes + buffer reuse (free)
  shape-keyed graph cache           jax.jit's shape/dtype-keyed cache
  op bulking                        XLA fusion
  export() -> symbol.json+params    jax.export (StableHLO) + params file
  SymbolBlock.imports               deserialize StableHLO, wrap as Block

Training state, PRNG, and BatchNorm aux-state (running mean/var) are threaded
through the traced function explicitly:
  - train/predict mode is a *static* switch: one jitted function per mode
  - a PRNG key is passed per call; Dropout etc. derive sub-keys by fold_in
  - aux updates are collected during trace and returned as extra outputs,
    then written back into the Parameters after each call
    (SURVEY.md §7 hard parts: "BatchNorm aux-state update inside jit")
"""
from __future__ import annotations

import json
import os
import re
import threading

import numpy as _np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from ..lint.retrace import RetraceMonitor
from ..ndarray.ndarray import NDArray
from ..ndarray import utils as nd_utils
from .. import _tape
from ..ndarray import random as _rnd
from .parameter import (Parameter, ParameterDict, Constant,
                        DeferredInitializationError, _bind_params)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "nn_block_scope"]


# ----------------------------------------------------------------------
# naming scope (reference: gluon/block.py _BlockScope)
# ----------------------------------------------------------------------

class _BlockScope:
    _local = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._local, "current", None)
        if current is None:
            if prefix is None:
                prefix = _global_count(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = f"{hint}{count}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._local, "current", None)
        _BlockScope._local.current = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return False
        _BlockScope._local.current = self._old_scope
        return False


_GLOBAL_COUNTERS = {}


def _global_count(hint):
    count = _GLOBAL_COUNTERS.get(hint, 0)
    _GLOBAL_COUNTERS[hint] = count + 1
    return f"{hint}{count}"


def nn_block_scope(block):
    return _BlockScope(block)


# ----------------------------------------------------------------------
# aux-update collector (BatchNorm running stats inside jit)
# ----------------------------------------------------------------------

class _AuxCollector(threading.local):
    def __init__(self):
        self.stack = []


_AUX = _AuxCollector()


class _aux_scope:
    def __enter__(self):
        _AUX.stack.append([])
        return _AUX.stack[-1]

    def __exit__(self, *exc):
        _AUX.stack.pop()
        return False


def record_aux_update(param, new_value):
    """Called by layers holding auxiliary (non-grad) state, e.g. BatchNorm.

    Inside a CachedOp trace the update is collected and threaded out of the
    jitted function; in eager mode it is applied immediately.
    """
    if _AUX.stack:
        _AUX.stack[-1].append((param, new_value))
    else:
        param._data._set_data(new_value.data if isinstance(new_value, NDArray)
                              else new_value)


# ----------------------------------------------------------------------
# Block
# ----------------------------------------------------------------------

class Block:
    """Base class for all neural network layers and models.
    Reference: gluon/block.py Block."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    # -- attribute magic ------------------------------------------------
    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)) and \
                    not isinstance(existing, type(value)):
                raise MXNetError(
                    f"Changing attribute type for {name} from "
                    f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self._children[name] = value
        elif isinstance(value, Parameter):
            self._reg_params[name] = value
        super().__setattr__(name, value)

    # -- public surface -------------------------------------------------
    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    @property
    def params(self):
        return self._params

    def name_scope(self):
        return self._scope

    def collect_params(self, select=None):
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + name: p for name, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def save_parameters(self, filename, deduplicate=False):
        """Reference: Block.save_parameters — structural dotted names."""
        params = self._collect_params_with_prefix()
        arg_dict = {}
        for name, param in params.items():
            if param._data is None:
                continue
            arg_dict[name] = param.data()
        nd_utils.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        loaded = nd_utils.load(filename)
        params = self._collect_params_with_prefix()
        # also accept prefix-style names saved by ParameterDict.save
        by_full_name = {p.name: p for p in params.values()}
        for name, value in loaded.items():
            key = name[4:] if name.startswith(("arg:", "aux:")) else name
            if key in params:
                params[key].set_data(value)
            elif key in by_full_name:
                by_full_name[key].set_data(value)
            elif not ignore_extra:
                raise MXNetError(
                    f"Parameter '{key}' loaded from file '{filename}' is not "
                    "present in this Block. Set ignore_extra=True to skip.")
        if not allow_missing:
            missing = [n for n, p in params.items()
                       if p._data is None and p._deferred_init is None
                       and n not in loaded and p.name not in loaded]
            if missing:
                raise MXNetError(
                    f"Parameters {missing} not found in file '{filename}'")

    # legacy aliases (reference deprecated names)
    save_params = save_parameters
    load_params = load_parameters

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for param in self._reg_params.values():
            param.cast(dtype)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        out = self(*inputs)
        n_params = builtins_sum(int(_np.prod(p.shape))
                                for p in self.collect_params().values()
                                if p.shape)
        print(f"{type(self).__name__}: {n_params} parameters, "
              f"output shape {out.shape if isinstance(out, NDArray) else '-'}")
        return out

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __repr__(self):
        s = f"{type(self).__name__}("
        for name, child in self._children.items():
            s += f"\n  ({name}): {child!r}"
        return s + ("\n)" if self._children else ")")


def builtins_sum(it):
    total = 0
    for x in it:
        total += x
    return total


def _abstract_trace(args):
    """True when the enclosing trace is a real (abstract) jit trace — the
    PRNG trace key installed by the tracing scope is itself a Tracer, or a
    tensor argument is. Eager passes under trace_scope (deferred-shape
    resolution) carry concrete keys/arrays and must NOT re-route through
    nested jit/checkpoint (their placement constraints fight commitments)."""
    stack = _rnd._STATE.trace_stack
    if stack and isinstance(stack[-1][0], jax.core.Tracer):
        return True
    return any(isinstance(getattr(a, "data", None), jax.core.Tracer)
               for a in args if a is not None)


# ----------------------------------------------------------------------
# CachedOp — the hybridize() engine
# ----------------------------------------------------------------------

class CachedOp:
    """Shape-cached jitted executor for a HybridBlock subtree.
    Reference: src/imperative/cached_op.{h,cc} (CachedOp::Forward)."""

    def __init__(self, block, static_alloc=False, static_shape=False,
                 inline_limit=2, remat=False):
        self.block = block
        self.static_alloc = static_alloc
        self.static_shape = static_shape
        self.remat = remat
        self._jitted = {}       # train_mode -> jitted fn
        self._param_objs = None  # ordered params
        self._out_tree = {}      # train_mode -> (n_out, structure)
        self._aux_params = {}    # train_mode -> [Parameter]
        self._in_avals = None    # last input signature (for export)
        self._none_pos = ()      # positions of None args (reinserted)
        self._raw = {}           # train_mode -> un-jitted pure fn
        # retrace observability (mx.lint runtime complement): every
        # distinct input signature is a jax.jit cache miss; the monitor
        # warns once past MXTPU_RETRACE_WARN distinct signatures
        self._retrace = RetraceMonitor(block.name or type(block).__name__)

    def _collect(self):
        if self._param_objs is None:
            items = sorted(self.block.collect_params().items())
            self._param_objs = [p for _, p in items]
        return self._param_objs

    def _make_pure(self, train):
        block = self.block
        cached = self

        def _pure(key, param_arrays, input_arrays):
            prev_train = _tape.set_training(train)
            params = cached._param_objs
            binding = {p: NDArray(a) for p, a in zip(params, param_arrays)}
            try:
                with _tape.trace_scope(), _bind_params(binding), \
                        _rnd.trace_key_scope(key), _aux_scope() as aux:
                    ins = [NDArray(a) for a in input_arrays]
                    for i in cached._none_pos:   # optional args elided
                        ins.insert(i, None)
                    out = block.forward(*ins)
            finally:
                _tape.set_training(prev_train)
            flat, tree = _flatten_output(out)
            cached._out_tree[train] = (len(flat), tree)
            cached._aux_params[train] = [p for p, _ in aux]
            outs = tuple(o.data for o in flat) + \
                tuple(v.data if isinstance(v, NDArray) else v for _, v in aux)
            return outs
        return _pure

    def _get_jitted(self, train, raw=False):
        """raw=True returns the (possibly checkpointed) pure fn WITHOUT the
        jax.jit wrapper — used when this block executes inside an enclosing
        trace: a nested jit would pin concrete captured args (PRNG key) to
        one device and fight mesh sharding constraints, while the raw fn
        inlines cleanly with the remat boundary intact."""
        store = self._raw if raw else self._jitted
        if train not in store:
            fn = self._make_pure(train)
            if self.remat:
                # jax.checkpoint: discard this block's activations in the
                # enclosing differentiated program and recompute them in
                # its backward — HBM for FLOPs. Survives inlining into an
                # outer jit (e.g. the fused DataParallelTrainer step), so
                # hybridize(remat=True) per encoder layer gives the classic
                # per-layer rematerialization schedule.
                fn = jax.checkpoint(fn)
            store[train] = fn if raw else jax.jit(fn)
        return store[train]

    def __call__(self, *args):
        # None args (optional masks etc.) fall back to the forward()
        # defaults — jit signatures carry arrays only; _make_pure reinserts
        # them by position
        none_pos = tuple(i for i, a in enumerate(args) if a is None)
        if none_pos != self._none_pos:
            self._none_pos = none_pos
            self._jitted = {}
            self._raw = {}
            self._out_tree = {}
        args = tuple(a for a in args if a is not None)
        params = self._collect()
        # Sparse-grad params can't ride jax.vjp of the fused program (its
        # cotangents are dense O(vocab)): dispatch the block imperatively
        # while grads are being recorded, so the Embedding op's row-sparse
        # pullback stays live. Mirrors the reference, where CachedOp defers
        # to FComputeEx imperative dispatch for sparse storage
        # (src/imperative/cached_op.cc storage-type fallback).
        if _tape.is_recording() and \
                any(p.grad_stype == "row_sparse" for p in params):
            if not getattr(self, "_warned_sparse_fallback", False):
                self._warned_sparse_fallback = True
                import warnings
                warnings.warn(
                    f"{self.block.name}: hybridized block has "
                    "row_sparse-grad parameters; training forward runs "
                    "imperatively to keep O(nnz) gradients (reference "
                    "sparse FComputeEx fallback)")
            return self.block.forward(*args)
        # deferred shapes: run one eager pause()-mode forward to resolve
        if any(p._data is None for p in params):
            with _tape.trace_scope():
                prev = _tape.set_training(_tape.is_training())
                try:
                    self.block.forward(*args)
                finally:
                    _tape.set_training(prev)
            self._param_objs = None
            params = self._collect()
        train = _tape.is_training()
        raw = _tape._STATE.trace_depth > 0
        if not raw:
            # one distinct (mode, shapes, dtypes) signature == one jit
            # cache miss == one full retrace + XLA compile; the raw path
            # inlines into an enclosing trace and has no cache of its own
            self._retrace.record(
                (train, self._none_pos,
                 tuple((tuple(a.data.shape), str(a.data.dtype))
                       for a in args)))
        jfn = self._get_jitted(train, raw=raw)
        key = _rnd.next_key()
        n_params = len(params)
        inputs = [p.data() for p in params] + list(args)
        self._in_avals = [jax.ShapeDtypeStruct(a.data.shape, a.data.dtype)
                          for a in args]

        if train not in self._out_tree:
            # trace abstractly once to learn output structure
            _ = jax.eval_shape(
                lambda *arrs: jfn(key, arrs[:n_params], arrs[n_params:]),
                *[x.data for x in inputs])
        n_out, tree = self._out_tree[train]
        aux_params = self._aux_params[train]
        total_out = n_out + len(aux_params)

        def fn(*arrs):
            outs = jfn(key, arrs[:n_params], arrs[n_params:])
            return outs[0] if total_out == 1 else outs

        outs, node = _tape.apply_op(fn, inputs, n_out=total_out,
                                    name=f"CachedOp({self.block.name})")
        ctx = args[0]._ctx if args else current_context()
        results = []
        for i in range(n_out):
            o = NDArray(outs[i], ctx)
            if node is not None:
                o._node = node
                o._out_index = i
            results.append(o)
        # write aux state back (running stats)
        for p, new_val in zip(aux_params, outs[n_out:]):
            p._data._set_data(new_val)
        return _unflatten_output(results, tree)


def _flatten_output(out):
    if isinstance(out, NDArray):
        return [out], "single"
    if isinstance(out, (list, tuple)):
        flat = []
        tree = []
        for o in out:
            f, t = _flatten_output(o)
            flat.extend(f)
            tree.append((t, len(f)))
        return flat, ("seq", type(out).__name__, tree)
    raise MXNetError(f"unsupported forward output type {type(out)}")


def _unflatten_output(flat, tree):
    if tree == "single":
        return flat[0]
    _, typename, subtrees = tree
    out = []
    i = 0
    for sub, n in subtrees:
        out.append(_unflatten_output(flat[i:i + n], sub))
        i += n
    return tuple(out) if typename == "tuple" else out


# ----------------------------------------------------------------------
# HybridBlock
# ----------------------------------------------------------------------

class HybridBlock(Block):
    """A Block that can be traced to XLA via hybridize().
    Reference: gluon/block.py HybridBlock (hybridize / export / infer_shape).
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  inline_limit=2, remat=None, **kwargs):
        # MXTPU_EAGER=1: serialize-everything debug switch — the reference's
        # MXNET_ENGINE_TYPE=NaiveEngine equivalent (SURVEY §2.1 row 1):
        # hybridize becomes a no-op so every op dispatches eagerly
        if active and os.environ.get("MXTPU_EAGER", "") == "1":
            active = False
        self._active = active
        if remat is None:   # unspecified: keep a previously-set schedule
            # (ancestor hybridize() recursion must not wipe per-layer remat)
            remat = self._flags.get("remat", False)
        self._flags = {"static_alloc": static_alloc,
                       "static_shape": static_shape,
                       "inline_limit": inline_limit,
                       "remat": remat}
        self._cached_op = None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def cast(self, dtype):
        self._cached_op = None
        super().cast(dtype)

    def infer_shape(self, *args):
        """Resolve deferred parameter shapes from input shapes. Layers
        override `_infer_shape_impl`; composite blocks resolve by running a
        shape-only forward."""
        self._infer_shape_impl(*args)

    def _infer_shape_impl(self, *args):
        raise DeferredInitializationError(
            f"{type(self).__name__} cannot infer parameter shapes "
            "automatically; run a forward pass first or set in_units/"
            "in_channels explicitly.")

    def __call__(self, *args, **kwargs):
        # inside an enclosing trace (outer CachedOp / fused trainer step)
        # blocks normally inline as plain ops — EXCEPT remat blocks, which
        # must still route through their jax.checkpoint-wrapped CachedOp so
        # the rematerialization boundary survives into the outer program.
        # Only when the enclosing trace is abstract (real jit tracing):
        # eager passes under trace_scope (deferred-shape resolution) carry
        # concrete arrays, where the boundary is meaningless and nested
        # placement constraints (ring attention) would fight commitments.
        in_trace = _tape._STATE.trace_depth > 0
        remat_route = self._flags.get("remat") and _abstract_trace(args)
        if self._active and not kwargs and (not in_trace or remat_route):
            if self._cached_op is None:
                self._cached_op = CachedOp(self, **{
                    k: v for k, v in self._flags.items()
                    if k in ("static_alloc", "static_shape", "inline_limit",
                             "remat")})
            return self._cached_op(*args)
        return super().__call__(*args, **kwargs)

    def forward(self, *args, **kwargs):
        """Gather this block's own params and call hybrid_forward.
        Children are invoked inside hybrid_forward as attributes."""
        from .. import ndarray as F
        try:
            params = {name: p.data() for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_init_params(*args)
            params = {name: p.data() for name, p in self._reg_params.items()}
        # per-block profiler annotation (SURVEY §5.1): inside a jit trace
        # this names the HLO region, so mx.profiler / TensorBoard traces
        # group ops by the Gluon block that produced them
        with jax.named_scope(self.name or type(self).__name__):
            return self.hybrid_forward(F, *args, **params, **kwargs)

    def _deferred_init_params(self, *args):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._data is None:
                p._finish_deferred_init()

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- export / import -----------------------------------------------
    def export(self, path, epoch=0):
        """Serialize the traced computation (StableHLO via jax.export) plus
        parameters. Writes, like the reference (Block.export):
          path-symbol.json   (metadata: param order, input avals, out tree)
          path-symbol.mlir   (the real artifact: serialized StableHLO)
          path-%04d.params   (arg:/aux:-prefixed parameter file)
        Requires at least one forward pass (to know input signatures) —
        same constraint as the reference. ``SymbolBlock.imports`` reloads
        and runs the artifact with NO Python model class."""
        cached = self._cached_op
        if cached is None or cached._in_avals is None:
            raise MXNetError(
                "Please first call block.hybridize() and then run forward "
                "with this block at least once before calling export.")
        from jax import export as jax_export
        params = cached._collect()
        arg_dict = {}
        for p in params:
            arg_dict[("aux:" if p.grad_req == "null" else "arg:") + p.name] = \
                p.data()
        nd_utils.save(f"{path}-{epoch:04d}.params", arg_dict)

        # Trace an inference-mode pure function over (params..., inputs...)
        # and serialize it. The PRNG key is baked in as a constant — dropout
        # etc. are identity in eval mode anyway.
        key = jax.random.PRNGKey(0)
        pure = cached._make_pure(False)
        n_params = len(params)

        def infer_fn(*arrs):
            outs = pure(key, arrs[:n_params], arrs[n_params:])
            n_out, _ = cached._out_tree[False]
            return outs[:n_out]

        in_avals = (
            [jax.ShapeDtypeStruct(p.shape, p.data().data.dtype)
             for p in params] + list(cached._in_avals))
        exp = jax_export.export(jax.jit(infer_fn))(*in_avals)
        with open(f"{path}-symbol.mlir", "wb") as f:
            f.write(exp.serialize())

        n_out, tree = cached._out_tree[False]
        meta = {
            "format": "mxnet_tpu-stablehlo-v1",
            "name": self.name,
            "params": [("aux:" if p.grad_req == "null" else "arg:") + p.name
                       for p in params],
            "inputs": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                       for a in cached._in_avals],
            "n_out": n_out,
            "out_tree": tree,
            "nodes": [],  # symbol.json stub for tools that parse it
        }
        with open(f"{path}-symbol.json", "w") as f:
            json.dump(meta, f, indent=2)
        return f"{path}-symbol.json"


class SymbolBlock(Block):
    """Run a previously exported computation as a Block.
    Reference: gluon/block.py SymbolBlock.imports(json, input_names, params).

    The portable artifact is the serialized-StableHLO ``-symbol.mlir`` next
    to the ``-symbol.json``: ``imports`` deserializes it (jax.export) and
    runs it with NO Python model class. A ``builder`` callable is an
    optional alternative that rebuilds the network from code (useful when
    further training is needed — the mlir path is inference-only)."""

    def __init__(self, outputs=None, inputs=None, params=None):
        super().__init__(prefix="", params=None)
        self._fn = outputs if callable(outputs) else None
        self._arg_params = params or {}
        self._exported = None
        self._param_arrays = None
        self._out_tree = None

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None,
                builder=None):
        with open(symbol_file) as f:
            meta = json.load(f)
        if builder is not None:
            net = builder()
            if param_file:
                net.load_parameters(param_file, ctx=ctx)
            return net
        mlir_file = str(symbol_file).replace("-symbol.json", "-symbol.mlir")
        if not os.path.exists(mlir_file):
            raise MXNetError(
                f"no serialized program next to {symbol_file} (expected "
                f"{mlir_file}); re-export with this version or pass "
                "`builder` (a zero-arg callable returning the network)")
        from jax import export as jax_export
        with open(mlir_file, "rb") as f:
            exported = jax_export.deserialize(f.read())
        blk = SymbolBlock()
        blk._exported = exported
        blk._out_tree = meta.get("out_tree", "single")
        param_names = meta.get("params", [])
        if param_names:
            if not param_file:
                raise MXNetError(
                    "exported program has parameters; pass param_file")
            loaded = nd_utils.load(param_file)
            try:
                blk._param_arrays = [loaded[n].data for n in param_names]
            except KeyError as e:
                raise MXNetError(
                    f"param file {param_file} is missing key {e} required "
                    f"by {symbol_file}")
        else:
            blk._param_arrays = []
        return blk

    def forward(self, *args):
        if self._exported is not None:
            arrs = [a.data if isinstance(a, NDArray) else jnp.asarray(a)
                    for a in args]
            ctx = args[0]._ctx if args and isinstance(args[0], NDArray) \
                else current_context()
            outs = self._exported.call(*self._param_arrays, *arrs)
            if not isinstance(outs, (list, tuple)):
                outs = (outs,)
            results = [NDArray(o, ctx) for o in outs]
            return _unflatten_output(results, _json_tree(self._out_tree))
        if self._fn is None:
            raise MXNetError("SymbolBlock has no callable attached")
        return self._fn(*args)


def _json_tree(tree):
    """Out-tree structure round-tripped through JSON (lists for tuples)."""
    if tree == "single":
        return "single"
    tag, typename, subtrees = tree
    return (tag, typename, [(_json_tree(s), n) for s, n in subtrees])
