// Native-runtime unit tests (mirrors the reference tests/cpp/ gtest layer,
// SURVEY §4: recordio roundtrip, prefetch ordering, error propagation).
// Plain asserts, no gtest dependency; exit 0 == pass.
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "../../src/mxtpu.h"

static std::string tmp_rec() {
  const char *dir = getenv("TMPDIR");
  std::string base = dir ? dir : "/tmp";
  return base + "/mxtpu_cpptest.rec";
}

static void test_recordio_roundtrip() {
  std::string path = tmp_rec();
  void *w = mxtpu_recordio_writer_open(path.c_str());
  assert(w && "writer open");
  std::vector<std::string> payloads = {"alpha", "bb", std::string(1000, 'x')};
  for (const auto &p : payloads) {
    int64_t rc = mxtpu_recordio_writer_write(w, p.data(), (int64_t)p.size());
    assert(rc >= 0 && "write");
  }
  assert(mxtpu_recordio_writer_close(w) == 0);

  void *r = mxtpu_recordio_open(path.c_str());
  assert(r && "reader open");
  assert(mxtpu_recordio_count(r) == (int64_t)payloads.size());
  for (size_t i = 0; i < payloads.size(); ++i) {
    void *buf = nullptr;
    int64_t n = mxtpu_recordio_read(r, (int64_t)i, &buf);
    assert(n == (int64_t)payloads[i].size());
    assert(memcmp(buf, payloads[i].data(), (size_t)n) == 0);
  }
  // out-of-range read fails with an error message, no crash
  void *buf = nullptr;
  int64_t n = mxtpu_recordio_read(r, 99, &buf);
  assert(n < 0);
  assert(mxtpu_last_error() && strlen(mxtpu_last_error()) > 0);
  mxtpu_recordio_close(r);
  printf("recordio roundtrip ok\n");
}

static void test_reader_missing_file() {
  void *r = mxtpu_recordio_open("/nonexistent/definitely_missing.rec");
  assert(r == nullptr);
  assert(mxtpu_last_error() && strlen(mxtpu_last_error()) > 0);
  printf("missing-file error path ok\n");
}

static void test_jpeg_decode_rejects_garbage() {
  uint8_t junk[64];
  memset(junk, 0xAB, sizeof(junk));
  uint8_t out[16 * 16 * 3];
  int32_t w = 0, h = 0, c = 0;
  int rc = mxtpu_jpeg_decode(junk, sizeof(junk), out, sizeof(out),
                             &h, &w, &c);
  assert(rc != 0 && "garbage must not decode");
  printf("jpeg garbage rejection ok\n");
}

int main() {
  test_recordio_roundtrip();
  test_reader_missing_file();
  test_jpeg_decode_rejects_garbage();
  printf("ALL CPP TESTS PASSED\n");
  return 0;
}
