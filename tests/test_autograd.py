"""Autograd tape tests.

Modelled on reference tests/python/unittest/test_autograd.py (SURVEY.md §4).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_basic_grad():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain_and_fanout():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        a = x * 2
        b = a + x        # x used twice -> contributions sum
        c = (b * b).sum()
    c.backward()
    # c = (3x)^2 -> dc/dx = 18x
    assert_almost_equal(x.grad, 18 * x.asnumpy())


def test_grad_req_add_accumulates():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, 3 * 2 * x.asnumpy())


def test_grad_req_write_overwrites():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="write")
    for _ in range(3):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_head_gradient():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(nd.array([1.0, 10.0, 100.0]))
    assert_almost_equal(x.grad, np.array([2.0, 20.0, 200.0], np.float32))


def test_detach_blocks_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = (y.detach() * x).sum()
    z.backward()
    # z = (2x).detach() * x -> dz/dx = 2x (detached factor constant)
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_block_grad_op():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(x * 3) * x
        z = y.sum()
    z.backward()
    assert_almost_equal(x.grad, 3 * x.asnumpy())


def test_pause_scope():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            w = x * 10    # not recorded
        z = (y + w).sum()
    z.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_is_recording_is_training():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
    with autograd.pause():
        assert not autograd.is_recording()


def test_autograd_grad_function():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
    g = autograd.grad(y, x)
    assert_almost_equal(g, 3 * x.asnumpy() ** 2)
    # .grad untouched by autograd.grad
    assert (x.grad.asnumpy() == 0).all()


def test_mark_variables():
    x = nd.array([1.0, 4.0])
    autograd.mark_variables([x], grad_reqs="write")
    with autograd.record():
        y = nd.sqrt(x).sum()
    y.backward()
    assert_almost_equal(x.grad, 0.5 / np.sqrt(x.asnumpy()))


def test_multi_output_op_grad():
    x = nd.array(np.arange(8, dtype=np.float32).reshape(2, 4))
    x.attach_grad()
    with autograd.record():
        a, b = nd.split(x, num_outputs=2, axis=1)
        loss = (a * 2 + b * 3).sum()
    loss.backward()
    expected = np.concatenate([np.full((2, 2), 2.0), np.full((2, 2), 3.0)], 1)
    assert_almost_equal(x.grad, expected.astype(np.float32))


def test_custom_function():
    class MySigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0, -1.0])
    x.attach_grad()
    f = MySigmoid()
    with autograd.record():
        y = f(x)
        z = y.sum()
    z.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-4)


def test_inplace_mutation_on_tape_raises():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with pytest.raises(mx.MXNetError):
            y += 1


def test_numeric_gradient_checker():
    x = nd.array(np.random.rand(3, 2).astype(np.float32) + 0.5)
    check_numeric_gradient(lambda a: (a * a + nd.exp(a)).sum(), [x],
                           rtol=5e-2, atol=1e-2)


def test_softmax_output_fused_grad():
    data = nd.array(np.random.rand(4, 5).astype(np.float32))
    label = nd.array([0, 1, 2, 3])
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, label)
    out.backward(nd.ones(out.shape))
    p = np.exp(data.asnumpy())
    p = p / p.sum(1, keepdims=True)
    oh = np.eye(5, dtype=np.float32)[[0, 1, 2, 3]]
    assert_almost_equal(data.grad, p - oh, rtol=1e-4)


def test_retain_graph_no_double_accumulation():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward(retain_graph=True)
    assert_almost_equal(x.grad, 2 * x.asnumpy())
    y.backward(retain_graph=True)
    # grad_req='write': second pass overwrites with the SAME value (no
    # stale-cotangent doubling)
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_grad_of_intermediate_variable():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = (y * y).sum()
    g = autograd.grad(z, y)
    assert_almost_equal(g, 2 * y.asnumpy())


def test_two_graphs_same_scope():
    # regression: backward on one graph must not destroy another graph
    # recorded in the same record scope (GAN D/G pattern)
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    x = mx.nd.array([2.0])
    y = mx.nd.array([3.0])
    x.attach_grad()
    y.attach_grad()
    with autograd.record():
        l1 = x * x
        l2 = y * y * y
    l1.backward()
    l2.backward()
    assert float(x.grad.asnumpy()[0]) == 4.0
    assert float(y.grad.asnumpy()[0]) == 27.0


def test_record_without_backward_no_leak():
    # regression: abandoning a recorded graph must not pin it globally —
    # the graph is owned by its output arrays only
    import gc
    import weakref
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, _tape
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        out = x * 2
    node_ref = weakref.ref(out._node)
    del out
    gc.collect()
    assert node_ref() is None


def test_higher_order_grad():
    """create_graph=True: grad-of-grad through the replayed tape
    (reference: tests/python/unittest/test_higher_order_grad.py)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        g = autograd.grad(y, [x], create_graph=True, retain_graph=True)[0]
        g2 = autograd.grad(g, [x])[0]
    assert float(g.asnumpy()[0]) == 12.0     # 3x^2
    assert float(g2.asnumpy()[0]) == 12.0    # 6x


def test_third_order_grad():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x * x
        g = autograd.grad(y, [x], create_graph=True, retain_graph=True)[0]
        g2 = autograd.grad(g, [x], create_graph=True, retain_graph=True)[0]
        g3 = autograd.grad(g2, [x])[0]
    assert float(g3.asnumpy()[0]) == 48.0    # 24x


def test_grad_multiple_variables():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    x = mx.nd.array([2.0])
    y = mx.nd.array([3.0])
    x.attach_grad()
    y.attach_grad()
    with autograd.record():
        z = x * y + x
        gx, gy = autograd.grad(z, [x, y], create_graph=True,
                               retain_graph=True)
    assert float(gx.asnumpy()[0]) == 4.0     # y + 1
    assert float(gy.asnumpy()[0]) == 2.0     # x


def test_get_symbol_replays_recorded_graph():
    """autograd.get_symbol (reference MXAutogradGetSymbol): the tape
    becomes a bindable Symbol whose execution replays the forward."""
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    w = nd.array([3.0, 4.0])
    w.attach_grad()
    with autograd.record():
        y = nd.exp(x) * w + nd.sin(x)
    s = autograd.get_symbol(y)
    assert s.list_arguments() == ["var0", "var1"]
    e = s.bind(None, dict(zip(s.list_arguments(), [x, w])))
    e.forward()
    np.testing.assert_allclose(e.outputs[0].asnumpy(), y.asnumpy(),
                               rtol=1e-6)
    # consumed tape (backward without retain_graph) raises with guidance
    y.backward()
    with pytest.raises(mx.MXNetError, match="retain_graph"):
        autograd.get_symbol(y)
    # works when retained
    with autograd.record():
        z = nd.tanh(x) * 2.0
    z.backward(retain_graph=True)
    s2 = autograd.get_symbol(z)
    e2 = s2.bind(None, {"var0": x})
    e2.forward()
    np.testing.assert_allclose(e2.outputs[0].asnumpy(), z.asnumpy(),
                               rtol=1e-6)


def test_get_symbol_guards():
    """Review findings: Function nodes get a precise diagnosis,
    multi-output ops execute once, traced symbols refuse JSON save."""
    from mxnet_tpu.ndarray.ndarray import apply_nary

    x = nd.array([2.0]); x.attach_grad()

    class Square(autograd.Function):
        def forward(self, a):
            return a * a
        def backward(self, g):
            return 2.0 * g

    with autograd.record():
        y = Square()(x) + 1.0
    with pytest.raises(mx.MXNetError, match="Function"):
        autograd.get_symbol(y)

    # multi-output op builds ONE node however many outputs are used
    calls = []
    def multi(a):
        calls.append(1)
        return a * 2.0, a * 3.0
    w = nd.array([1.0, 2.0]); w.attach_grad()
    with autograd.record():
        o = apply_nary(multi, [w], n_out=2)
        z = o[0] * o[1]
    s = autograd.get_symbol(z)
    calls.clear()
    e = s.bind(None, {"var0": w})
    e.forward()
    assert calls == [1], calls          # fn executed exactly once
    np.testing.assert_allclose(e.outputs[0].asnumpy(), z.asnumpy(),
                               rtol=1e-6)
    with pytest.raises(mx.MXNetError, match="JSON"):
        s.tojson()
