"""Gluon Block/HybridBlock/Parameter/Trainer tests.

Modelled on reference tests/python/unittest/test_gluon.py (SURVEY.md §4).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(3, 4))
    p.initialize(ctx=mx.cpu())
    assert p.data().shape == (3, 4)
    assert p.grad() is not None
    p.set_data(nd.ones((3, 4)))
    assert (p.data().asnumpy() == 1).all()
    with pytest.raises(mx.MXNetError):
        gluon.Parameter("w2", shape=(0, 3)).initialize()


def test_parameter_deferred_init():
    dense = nn.Dense(5)
    dense.initialize()
    with pytest.raises(gluon.parameter.DeferredInitializationError
                       if hasattr(gluon, "parameter") else Exception):
        dense.weight.data()
    out = dense(nd.ones((2, 7)))
    assert out.shape == (2, 5)
    assert dense.weight.shape == (5, 7)


def test_dense_forward_values():
    dense = nn.Dense(3, use_bias=True, in_units=4)
    dense.initialize()
    dense.weight.set_data(nd.ones((3, 4)))
    dense.bias.set_data(nd.array([1.0, 2.0, 3.0]))
    out = dense(nd.ones((2, 4)))
    assert_almost_equal(out, np.array([[5, 6, 7], [5, 6, 7]], np.float32))


def test_dense_flatten_false():
    dense = nn.Dense(6, flatten=False)
    dense.initialize()
    out = dense(nd.ones((2, 5, 4)))
    assert out.shape == (2, 5, 6)


def test_sequential_and_getitem():
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.Dense(4), nn.Dense(2))
    net.initialize()
    assert len(net) == 3
    out = net(nd.ones((3, 10)))
    assert out.shape == (3, 2)
    assert isinstance(net[0], nn.Dense)


def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = nd.array(np.random.rand(5, 8).astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-5)


def test_hybridize_grads_match():
    def build():
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="tanh"), nn.Dense(1))
        net.initialize()
        return net

    x = nd.array(np.random.rand(4, 6).astype(np.float32))
    grads = []
    for hybrid in (False, True):
        net = build()
        if hybrid:
            net.hybridize()
        with autograd.record():
            y = net(x).sum()
        y.backward()
        # insertion (structural) order, NOT sorted-by-name: the global
        # name counters differ between the two builds and "dense10_" sorts
        # before "dense9_", which would misalign the zip
        grads.append([p.grad().asnumpy()
                      for _, p in net.collect_params().items()
                      if p.grad_req != "null"])
    for g0, g1 in zip(*grads):
        assert_almost_equal(g0, g1, rtol=1e-4)


def test_conv2d_shapes():
    conv = nn.Conv2D(16, kernel_size=3, strides=2, padding=1)
    conv.initialize()
    out = conv(nd.ones((2, 3, 32, 32)))
    assert out.shape == (2, 16, 16, 16)
    assert conv.weight.shape == (16, 3, 3, 3)


def test_conv2d_groups():
    conv = nn.Conv2D(8, kernel_size=3, groups=4, in_channels=8)
    conv.initialize()
    out = conv(nd.ones((1, 8, 10, 10)))
    assert out.shape == (1, 8, 8, 8)
    assert conv.weight.shape == (8, 2, 3, 3)


def test_conv_transpose():
    deconv = nn.Conv2DTranspose(4, kernel_size=2, strides=2)
    deconv.initialize()
    out = deconv(nd.ones((1, 3, 8, 8)))
    assert out.shape == (1, 4, 16, 16)


def test_pooling_variants():
    x = nd.array(np.random.rand(1, 2, 9, 9).astype(np.float32))
    assert nn.MaxPool2D(2)(x).shape == (1, 2, 4, 4)
    assert nn.MaxPool2D(2, ceil_mode=True)(x).shape == (1, 2, 5, 5)
    assert nn.AvgPool2D(3, strides=2)(x).shape == (1, 2, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (1, 2, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (1, 2, 1, 1)
    expected = x.asnumpy().max(axis=(2, 3), keepdims=True)
    assert_almost_equal(nn.GlobalMaxPool2D()(x), expected)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = nd.array(np.random.rand(8, 4, 5, 5).astype(np.float32) * 10)
    with autograd.record():
        out_train = bn(x)
    m = out_train.asnumpy().mean(axis=(0, 2, 3))
    v = out_train.asnumpy().var(axis=(0, 2, 3))
    assert np.allclose(m, 0, atol=1e-3)
    assert np.allclose(v, 1, atol=1e-2)
    rm = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm, 0)  # running stats updated
    out_eval = bn(x)  # eval mode uses running stats
    assert not np.allclose(out_eval.asnumpy(), out_train.asnumpy())


def test_layernorm_embedding_dropout():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    x = nd.array(np.random.rand(3, 6).astype(np.float32))
    out = ln(x)
    assert np.allclose(out.asnumpy().mean(-1), 0, atol=1e-5)

    emb = nn.Embedding(10, 4)
    emb.initialize()
    e = emb(nd.array([1, 5, 9]))
    assert e.shape == (3, 4)

    do = nn.Dropout(0.5)
    x2 = nd.ones((100, 100))
    out_eval = do(x2)
    assert_almost_equal(out_eval, x2)  # identity outside training
    with autograd.record():
        out_train = do(x2)
    frac_zero = float((out_train.asnumpy() == 0).mean())
    assert 0.4 < frac_zero < 0.6


def test_activation_layers():
    x = nd.array([-2.0, -0.5, 0.5, 2.0])
    assert_almost_equal(nn.Activation("relu")(x),
                        np.maximum(x.asnumpy(), 0))
    assert_almost_equal(nn.LeakyReLU(0.1)(x),
                        np.where(x.asnumpy() > 0, x.asnumpy(),
                                 0.1 * x.asnumpy()), rtol=1e-4)
    prelu = nn.PReLU()
    prelu.initialize()
    out = prelu(x)
    assert out.shape == x.shape


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    net.weight.set_data(nd.array([[1.0, 1.0]]))
    net.bias.set_data(nd.array([0.0]))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.array([[1.0, 2.0]])
    with autograd.record():
        y = net(x).sum()
    y.backward()
    trainer.step(1)
    # dw = x -> w_new = w - 0.1 * x
    assert_almost_equal(net.weight.data(), np.array([[0.9, 0.8]], np.float32))


def test_trainer_stale_grad_raises():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd", {})
    with pytest.raises(mx.MXNetError):
        trainer.step(1)
    trainer.step(1, ignore_stale_grad=True)


def test_save_load_parameters(tmp_path):
    fname = str(tmp_path / "net.params")
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    x = nd.ones((1, 3))
    ref = net(x).asnumpy()
    net.save_parameters(fname)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4), nn.Dense(2))
    net2.load_parameters(fname)
    assert_almost_equal(net2(x), ref)


def test_collect_params_select():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    net(nd.ones((1, 3)))
    all_params = net.collect_params()
    weights = net.collect_params(".*weight")
    assert len(weights) == 2
    assert len(all_params) == 4


def test_losses():
    pred = nd.array(np.random.rand(4, 5).astype(np.float32))
    label = nd.array([0, 1, 2, 3])
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    assert l.shape == (4,)
    logp = pred.asnumpy() - np.log(
        np.exp(pred.asnumpy()).sum(1, keepdims=True))
    expected = -logp[np.arange(4), [0, 1, 2, 3]]
    assert_almost_equal(l, expected, rtol=1e-4)

    p2 = nd.array([[1.0, 2.0]])
    t2 = nd.array([[0.5, 1.0]])
    l2 = gluon.loss.L2Loss()(p2, t2)
    assert_almost_equal(l2, np.array([(0.25 + 1.0) / 2 / 2], np.float32))
    l1 = gluon.loss.L1Loss()(p2, t2)
    assert_almost_equal(l1, np.array([0.75], np.float32))
    bce = gluon.loss.SigmoidBCELoss()(p2, nd.array([[1.0, 0.0]]))
    assert bce.shape == (1,)
    hl = gluon.loss.HuberLoss()(p2, t2)
    assert hl.shape == (1,)


def test_split_and_load():
    data = nd.array(np.arange(12, dtype=np.float32).reshape(6, 2))
    slices = gluon.utils.split_and_load(data, [mx.cpu(0)])
    assert len(slices) == 1 and slices[0].shape == (6, 2)
    parts = gluon.utils.split_data(data, 3)
    assert [p.shape for p in parts] == [(2, 2)] * 3
    with pytest.raises(mx.MXNetError):
        gluon.utils.split_data(data, 4)


def test_clip_global_norm():
    arrays = [nd.ones((2, 2)) * 3, nd.ones((3,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0)
    new_norm = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(new_norm - 1.0) < 1e-4


def test_block_repr_and_cast():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.cast("float16")
    assert net.weight.data().data.dtype == np.float16
    net.cast("float32")
    out = net(nd.ones((1, 3)))
    assert out.data.dtype == np.float32


def test_lambda_blocks():
    lam = nn.Lambda("relu")
    assert_almost_equal(lam(nd.array([-1.0, 1.0])), [0.0, 1.0])
    hlam = nn.HybridLambda(lambda F, x: x * 2)
    assert_almost_equal(hlam(nd.array([1.0, 2.0])), [2.0, 4.0])


def test_embedding_grad_is_scatter():
    emb = nn.Embedding(5, 3)
    emb.initialize()
    idx = nd.array([1, 1, 4])
    with autograd.record():
        out = emb(idx).sum()
    out.backward()
    g = emb.weight.grad().asnumpy()
    assert np.allclose(g[1], 2.0)
    assert np.allclose(g[4], 1.0)
    assert np.allclose(g[0], 0.0)


def test_clip_global_norm_on_param_grads():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    with autograd.record():
        out = (net(nd.ones((4, 3)) * 100) ** 2).sum()
    out.backward()
    grads = [p.grad() for p in net.collect_params().values()]
    gluon.utils.clip_global_norm(grads, 0.5)
    total = np.sqrt(sum((p.grad().asnumpy() ** 2).sum()
                        for p in net.collect_params().values()))
    assert abs(total - 0.5) < 1e-3  # clip reached the stored grads


def test_batchnorm_eager_grad_matches_hybrid():
    def build():
        mx.random.seed(3)
        net = nn.HybridSequential()
        net.add(nn.Conv2D(4, 3, in_channels=2), nn.BatchNorm(in_channels=4),
                nn.Activation("relu"), nn.Flatten(), nn.Dense(2))
        net.initialize()
        return net

    x = nd.array(np.random.RandomState(0).rand(4, 2, 8, 8).astype("float32"))
    grads = []
    for hybrid in (False, True):
        net = build()
        if hybrid:
            net.hybridize()
        with autograd.record():
            y = net(x).sum()
        y.backward()
        grads.append([p.grad().asnumpy()
                      for _, p in sorted(net.collect_params().items())
                      if p.grad_req != "null"])
    for g0, g1 in zip(*grads):
        assert_almost_equal(g0, g1, rtol=1e-3, atol=1e-4)


def test_trainer_state_counters_survive_save_load(tmp_path):
    fname = str(tmp_path / "trainer.states")
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    x = nd.ones((2, 2))
    for _ in range(5):
        with autograd.record():
            y = net(x).sum()
        y.backward()
        trainer.step(2)
    assert trainer._optimizer.num_update == 5
    trainer.save_states(fname)

    net2 = nn.Dense(1, in_units=2)
    net2.initialize()
    trainer2 = gluon.Trainer(net2.collect_params(), "adam",
                             {"learning_rate": 0.01})
    trainer2.load_states(fname)
    assert trainer2._optimizer.num_update == 5
    assert trainer2._optimizer._index_update_count[0] == 5


def test_pooling_int_dtype_and_sequence_last_axis1():
    xi = mx.nd.array(np.arange(16, dtype=np.int32).reshape(1, 1, 4, 4))
    out = mx.nd.Pooling(xi, kernel=(2, 2), stride=(2, 2), pool_type="sum")
    assert out.asnumpy()[0, 0, 0, 0] == 0 + 1 + 4 + 5
    data = mx.nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))  # B,T
    sl = mx.nd.array([1, 2, 4])
    last = mx.nd.SequenceLast(data, sequence_length=sl,
                              use_sequence_length=True, axis=1)
    assert_almost_equal(last, np.array([0.0, 5.0, 11.0], np.float32))


def test_dataloader_workers_and_early_stop():
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    ds = gluon.data.ArrayDataset(
        mx.nd.array(np.arange(64).reshape(32, 2)),
        mx.nd.array(np.arange(32)))
    loader = gluon.data.DataLoader(ds, batch_size=4, num_workers=3)
    seen = []
    for data, label in loader:
        seen.append(label.asnumpy())
    assert np.concatenate(seen).tolist() == list(range(32))
    # abandoning mid-epoch must not deadlock or leak blocked threads
    for _ in range(3):
        it = iter(loader)
        next(it)
        del it


def test_space_to_depth_stem_matches_7x7_conv():
    """MLPerf-style stem rewrite must be numerically exact (same weight)."""
    from mxnet_tpu.gluon.model_zoo.vision.resnet import SpaceToDepthStem
    rng = np.random.RandomState(7)
    x = nd.array(rng.randn(2, 3, 32, 32).astype(np.float32))
    w = rng.randn(16, 3, 7, 7).astype(np.float32)
    ref = nn.Conv2D(16, 7, 2, 3, use_bias=False, in_channels=3)
    ref.initialize()
    ref.weight.set_data(nd.array(w))
    stem = SpaceToDepthStem(16)
    stem.initialize()
    stem.weight.set_data(nd.array(w))
    assert_almost_equal(stem(x).asnumpy(), ref(x).asnumpy(),
                        rtol=1e-4, atol=1e-4)
    stem.hybridize()
    assert_almost_equal(stem(x).asnumpy(), ref(x).asnumpy(),
                        rtol=1e-4, atol=1e-4)
    # odd spatial sizes pad-to-even and stay exact (7x7/p3 reads zeros
    # past the edge either way)
    x_odd = nd.array(rng.randn(2, 3, 33, 33).astype(np.float32))
    assert_almost_equal(stem(x_odd).asnumpy(), ref(x_odd).asnumpy(),
                        rtol=1e-4, atol=1e-4)
    # full model: stock checkpoint loads into the s2d variant (param is
    # conv0_weight in both) and outputs match
    import os
    import tempfile
    from mxnet_tpu.gluon.model_zoo import vision
    std = vision.resnet18_v1(classes=10)
    std.initialize()
    xm = nd.array(rng.randn(1, 3, 64, 64).astype(np.float32))
    y_std = std(xm)
    path = os.path.join(tempfile.mkdtemp(), "r18.params")
    std.save_parameters(path)
    net = vision.resnet18_v1(classes=10, s2d_stem=True)
    net.load_parameters(path)
    assert_almost_equal(net(xm).asnumpy(), y_std.asnumpy(),
                        rtol=1e-4, atol=1e-4)


def test_space_to_depth_stem_non_rgb_inputs():
    """in_channels != 3 works when declared, errors clearly when not
    (advisor round-3 finding: the stock stem defers in_channels)."""
    from mxnet_tpu.gluon.model_zoo.vision.resnet import SpaceToDepthStem
    rng = np.random.RandomState(11)
    x = nd.array(rng.randn(2, 4, 16, 16).astype(np.float32))
    w = rng.randn(8, 4, 7, 7).astype(np.float32)
    ref = nn.Conv2D(8, 7, 2, 3, use_bias=False, in_channels=4)
    ref.initialize()
    ref.weight.set_data(nd.array(w))
    stem = SpaceToDepthStem(8, in_channels=4)
    stem.initialize()
    stem.weight.set_data(nd.array(w))
    assert_almost_equal(stem(x).asnumpy(), ref(x).asnumpy(),
                        rtol=1e-4, atol=1e-4)
    stem3 = SpaceToDepthStem(8)
    stem3.initialize()
    with pytest.raises(mx.MXNetError, match="in_channels"):
        stem3(x)
    # threads through the model-zoo API
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1(classes=4, s2d_stem=True, stem_in_channels=1)
    net.initialize()
    xg = nd.array(rng.randn(2, 1, 32, 32).astype(np.float32))
    assert net(xg).shape == (2, 4)


def test_hybridize_remat_gradient_parity():
    """hybridize(remat=True) must be bit-compatible with the plain jit
    path while carrying the jax.checkpoint schedule."""
    import jax

    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"),
                nn.Dense(16, activation="relu"), nn.Dense(4))
        return net

    rng = np.random.RandomState(0)
    x_np = rng.randn(4, 8).astype(np.float32)
    net_a, net_b = build(), build()
    net_a.initialize()
    net_b.initialize()
    net_a(nd.array(x_np))
    net_b(nd.array(x_np))
    for (_, p), (_, q) in zip(sorted(net_a.collect_params().items()),
                              sorted(net_b.collect_params().items())):
        q.set_data(nd.array(p.data().asnumpy()))
    net_a.hybridize()
    net_b.hybridize(remat=True)
    xa, xb = nd.array(x_np), nd.array(x_np)
    xa.attach_grad()
    xb.attach_grad()
    with autograd.record():
        la = (net_a(xa) ** 2).sum()
    la.backward()
    with autograd.record():
        lb = (net_b(xb) ** 2).sum()
    lb.backward()
    assert_almost_equal(la.asnumpy(), lb.asnumpy(), rtol=1e-6)
    assert_almost_equal(xa.grad.asnumpy(), xb.grad.asnumpy(), rtol=1e-6)


def test_bert_encoder_remat():
    """Per-cell remat on BERT: same outputs/grads as the plain path, and
    the jax.checkpoint boundary survives into the fused trainer step."""
    import jax

    from mxnet_tpu.gluon.model_zoo.nlp.bert import get_bert_model
    from mxnet_tpu.parallel import make_mesh
    from mxnet_tpu.parallel.data_parallel import DataParallelTrainer

    def build():
        net = get_bert_model(num_layers=2, units=32, hidden_size=64,
                             num_heads=4, vocab_size=100, max_length=16,
                             dropout=0.0, use_decoder=False)
        net.initialize()
        return net

    rng = np.random.RandomState(0)
    tokens = nd.array(rng.randint(0, 100, (2, 8)), dtype="int32")
    types = nd.zeros((2, 8), dtype="int32")
    label = nd.array(rng.randint(0, 2, (2,)), dtype="int32")
    net_a, net_b = build(), build()
    net_a(tokens, types)
    net_b(tokens, types)       # materialize deferred shapes
    for (_, p), (_, q) in zip(sorted(net_a.collect_params().items()),
                              sorted(net_b.collect_params().items())):
        q.set_data(nd.array(p.data().asnumpy()))
    net_b.encoder.remat(True)
    # eager-outer parity
    assert_almost_equal(net_b(tokens, types)[-1].asnumpy(),
                        net_a(tokens, types)[-1].asnumpy(),
                        rtol=1e-5, atol=1e-5)
    # fused-trainer parity over two optimizer steps (remat cells route
    # through their checkpointed CachedOp inside the outer trace)
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for net in (net_a, net_b):
        mesh = make_mesh({"dp": 1}, devices=jax.devices()[:1])
        tr = DataParallelTrainer(net, lambda o, l: ce(o[-1], l), "sgd",
                                 {"learning_rate": 0.1}, mesh=mesh)
        losses.append((float(tr.step(tokens, types, label).asnumpy()),
                       float(tr.step(tokens, types, label).asnumpy())))
    assert np.allclose(losses[0], losses[1], rtol=1e-5), losses
    # an ancestor hybridize() must not wipe the per-cell remat schedule
    net_b.hybridize()
    cells = net_b.encoder.transformer_cells._children.values()
    assert all(c._flags.get("remat") for c in cells)


def test_identity_and_concatenate():
    """Reference basic_layers Identity/HybridConcatenate (>=1.6)."""
    ident = nn.Identity()
    x = nd.array(np.random.RandomState(0).rand(2, 3).astype(np.float32))
    assert_almost_equal(ident(x).asnumpy(), x.asnumpy())
    cat = nn.HybridConcatenate(axis=-1)
    cat.add(nn.Dense(4), nn.Dense(2), nn.Identity())
    cat.initialize()
    out = cat(x)
    assert out.shape == (2, 9)
    cat.hybridize()
    assert_almost_equal(cat(x).asnumpy(), out.asnumpy(), rtol=1e-5)
    with autograd.record():
        loss = cat(x).sum()
    loss.backward()
    assert isinstance(nn.Concatenate(axis=1), nn.HybridConcatenate)


def test_dataloader_process_workers():
    """thread_pool=False: true worker PROCESSES (reference default
    semantics) — spawned with CPU-only jax, dataset shipped via pickle
    (NDArray.__reduce__ -> numpy), batches returned as numpy and
    re-materialized in the parent."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    ds = gluon.data.ArrayDataset(
        mx.nd.array(np.arange(48).reshape(24, 2)),
        mx.nd.array(np.arange(24)))
    loader = gluon.data.DataLoader(ds, batch_size=4, num_workers=2,
                                   thread_pool=False)
    for _ in range(2):   # pool persists across epochs
        seen = []
        for data, label in loader:
            assert data.shape == (4, 2)
            seen.append(label.asnumpy())
        assert np.concatenate(seen).tolist() == list(range(24))
    del loader


def test_ndarray_pickle_roundtrip():
    import pickle
    import numpy as np
    import mxnet_tpu as mx
    a = mx.nd.array(np.arange(6.0).reshape(2, 3))
    b = pickle.loads(pickle.dumps(a))
    assert isinstance(b, mx.nd.NDArray)
    np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
