"""ResNeSt (split-attention) zoo tests — GluonCV resnest.py/splat.py parity
(the reference fork author's model family)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.model_zoo.vision.resnest import (ResNeSt,
                                                      SplitAttentionConv)
from mxnet_tpu.test_utils import assert_almost_equal


def test_split_attention_shapes_and_gate():
    c = SplitAttentionConv(8, 3, padding=1, radix=2)
    c.initialize()
    x = nd.array(np.random.RandomState(0).randn(2, 8, 8, 8).astype(np.float32))
    out = c(x)
    assert out.shape == (2, 8, 8, 8)
    # radix=1 degenerates to sigmoid (SE) gating, same shape
    c1 = SplitAttentionConv(8, 3, padding=1, radix=1)
    c1.initialize()
    assert c1(x).shape == (2, 8, 8, 8)


def test_split_attention_hybrid_parity_and_grad():
    c = SplitAttentionConv(8, 3, padding=1, radix=2)
    c.initialize()
    x = nd.array(np.random.RandomState(1).randn(2, 8, 8, 8).astype(np.float32))
    y_eager = c(x)
    c.hybridize()
    y_hyb = c(x)
    assert_almost_equal(y_hyb.asnumpy(), y_eager.asnumpy(),
                        rtol=1e-5, atol=1e-5)
    x.attach_grad()
    with autograd.record():
        loss = c(x).sum()
    loss.backward()
    assert float(np.abs(x.grad.asnumpy()).sum()) > 0


@pytest.mark.slow
def test_resnest_tiny_end_to_end():
    net = ResNeSt([1, 1, 1, 1], classes=10)
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.RandomState(2).randn(2, 3, 64, 64)
                 .astype(np.float32))
    with autograd.record():
        out = net(x)
        loss = out.sum()
    loss.backward()
    assert out.shape == (2, 10)


# slow-marked (ISSUE 18 tier-1 headroom): zoo registration/forwards
# stay covered by the detection name sweep + resnest unit tests
@pytest.mark.slow
def test_resnest_zoo_registration():
    net = vision.get_model("resnest50", classes=7)
    assert isinstance(net, ResNeSt)
    # resnest50 parameter count ~27.5M at 1000 classes (paper Table 1);
    # with 7 classes subtract most of the fc: 25.4M +- 10%
    net.initialize()
    net(nd.zeros((1, 3, 64, 64)))   # materialize deferred shapes
    n = sum(int(np.prod(p.shape)) for p in net.collect_params().values())
    assert 23e6 < n < 28e6, n


def test_avgpool_hybridized_backward_regression():
    """reduce_window with a traced init value broke vjp-of-jit: AvgPool2D
    under hybridize()+record() must differentiate (found via ResNeSt avd)."""
    for layer in (nn.AvgPool2D(2, 2),
                  nn.AvgPool2D(3, 2, padding=1, count_include_pad=False)):
        layer.hybridize()
        x = nd.array(np.random.RandomState(3).randn(2, 4, 8, 8)
                     .astype(np.float32))
        x.attach_grad()
        with autograd.record():
            loss = layer(x).sum()
        loss.backward()
        g = x.grad.asnumpy()
        assert float(np.abs(g).sum()) > 0


@pytest.mark.slow
def test_resnext_and_se_resnet():
    """ResNeXt grouped bottleneck + SE gate (gluoncv resnext.py/senet.py)."""
    from mxnet_tpu.gluon.model_zoo.vision.resnext import (ResNeXt, SEBlock,
                                                          resnext50_32x4d,
                                                          se_resnet50)
    x = nd.array(np.random.RandomState(0).randn(2, 3, 64, 64)
                 .astype(np.float32))
    tiny = ResNeXt([1, 1, 1, 1], cardinality=4, bottleneck_width=4,
                   classes=10)
    tiny.initialize()
    tiny.hybridize()
    with autograd.record():
        out = tiny(x)
        loss = out.sum()
    loss.backward()
    assert out.shape == (2, 10)
    # SE gate scales channels in [0, 1]
    se = SEBlock(8)
    se.initialize()
    h = nd.array(np.random.RandomState(1).randn(1, 8, 4, 4)
                 .astype(np.float32))
    g = se(h)
    assert g.shape == h.shape
    # full model param counts: resnext50_32x4d ~25.0M, se_resnet50 ~28.1M
    for ctor, lo, hi in ((resnext50_32x4d, 22e6, 27e6),
                         (se_resnet50, 25e6, 31e6)):
        net = ctor(classes=10)
        net.initialize()
        net(nd.zeros((1, 3, 64, 64)))
        n = sum(int(np.prod(p.shape))
                for p in net.collect_params().values())
        assert lo < n < hi, (ctor.__name__, n)
    assert vision.get_model("resnext50_32x4d", classes=5) is not None
    assert vision.get_model("se_resnet50", classes=5) is not None
