"""HybridBlock.export / SymbolBlock.imports roundtrip + examples smoke
(reference: tests/python/unittest/test_gluon.py export tests; the examples
are the reference's acceptance surface, SURVEY.md §2.4)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
nd = mx.nd


def _cpu_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["MXTPU_SYNTHETIC_DATA"] = "1"
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and ".axon_site" not in p] + [REPO])
    return env


def test_export_import_roundtrip(tmp_path):
    # export saves FULL param names (arg:dense0_weight ...), so the
    # reloading net must use the same name prefixes — reference semantics
    # (load_parameters of an export'd file needs matching prefixes;
    # structural matching is save_parameters' job)
    def build(prefix):
        net = gluon.nn.HybridSequential(prefix=prefix)
        with net.name_scope():
            net.add(gluon.nn.Dense(8, activation="relu"))
            net.add(gluon.nn.Dense(3))
        return net

    net = build("m_")
    net.initialize()
    net.hybridize()
    x = nd.random.uniform(shape=(2, 5))
    ref = net(x).asnumpy()
    prefix = str(tmp_path / "model")
    net.export(prefix)
    files = os.listdir(tmp_path)
    assert any(f.endswith(".params") for f in files), files
    assert any(f.endswith("-symbol.json") for f in files), files
    net2 = build("m_")
    param_file = [f for f in files if f.endswith(".params")][0]
    net2.load_parameters(str(tmp_path / param_file))
    np.testing.assert_allclose(net2(x).asnumpy(), ref, rtol=1e-5)


def _run_example(name, *args, timeout=420):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name), *args],
        capture_output=True, text=True, timeout=timeout, env=_cpu_env())
    assert r.returncode == 0, f"{name} failed:\n{r.stdout}\n{r.stderr}"
    return r.stdout


@pytest.mark.slow
def test_example_image_classification():
    out = _run_example("image_classification.py", "--num-epochs", "2")
    assert "final validation" in out


@pytest.mark.slow
def test_example_dcgan():
    out = _run_example("dcgan.py", "--num-iters", "5")
    assert "ok" in out


@pytest.mark.slow
def test_example_sparse_fm():
    out = _run_example("sparse_factorization_machine.py")
    assert "ok" in out
