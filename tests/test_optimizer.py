"""Optimizers vs pure-numpy reference updates.

Models the reference's tests/python/unittest/test_optimizer.py: the fused
update op must match a transparent python implementation step for step.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt

nd = mx.nd


def _run(optimizer, w0, grads, **kw):
    """Apply `optimizer` to one weight over a grad sequence; return final."""
    w = nd.array(w0.copy())
    state = optimizer.create_state(0, w)
    for g in grads:
        optimizer.update(0, w, nd.array(g), state)
    return w.asnumpy()


@pytest.fixture
def problem():
    rng = np.random.RandomState(7)
    w0 = rng.randn(6).astype(np.float32)
    grads = [rng.randn(6).astype(np.float32) for _ in range(5)]
    return w0, grads


def test_sgd_matches_reference(problem):
    w0, grads = problem
    lr, wd = 0.1, 0.01
    out = _run(opt.SGD(learning_rate=lr, wd=wd), w0, grads)
    w = w0.copy()
    for g in grads:
        w = w - lr * (g + wd * w)
    np.testing.assert_allclose(out, w, rtol=1e-5)


def test_sgd_momentum_matches_reference(problem):
    w0, grads = problem
    lr, mom, wd = 0.1, 0.9, 0.01
    out = _run(opt.SGD(learning_rate=lr, momentum=mom, wd=wd), w0, grads)
    w = w0.copy()
    m = np.zeros_like(w)
    for g in grads:
        m = mom * m - lr * (g + wd * w)
        w = w + m
    np.testing.assert_allclose(out, w, rtol=1e-5)


def test_adam_matches_reference(problem):
    w0, grads = problem
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    out = _run(opt.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps),
               w0, grads)
    w = w0.copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t, g in enumerate(grads, 1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        w = w - lr_t * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(out, w, rtol=1e-4, atol=1e-6)


def test_adagrad_matches_reference(problem):
    w0, grads = problem
    lr, eps = 0.1, 1e-7
    out = _run(opt.AdaGrad(learning_rate=lr, eps=eps), w0, grads)
    w = w0.copy()
    h = np.zeros_like(w)
    for g in grads:
        h = h + g * g
        w = w - lr * g / (np.sqrt(h) + eps)
    np.testing.assert_allclose(out, w, rtol=1e-4, atol=1e-6)


def test_rmsprop_matches_reference(problem):
    w0, grads = problem
    lr, gamma1, eps = 0.01, 0.9, 1e-8
    out = _run(opt.RMSProp(learning_rate=lr, gamma1=gamma1, epsilon=eps),
               w0, grads)
    w = w0.copy()
    n = np.zeros_like(w)
    for g in grads:
        n = (1 - gamma1) * g * g + gamma1 * n
        w = w - lr * g / np.sqrt(n + eps)
    np.testing.assert_allclose(out, w, rtol=1e-4, atol=1e-5)


def test_signum_signs_only(problem):
    w0, grads = problem
    out = _run(opt.Signum(learning_rate=0.1, momentum=0.0, wd_lh=0.0),
               w0, grads)
    w = w0.copy()
    for g in grads:
        w = w - 0.1 * np.sign(g)
    np.testing.assert_allclose(out, w, rtol=1e-5)


def test_rescale_and_clip_gradient(problem):
    w0, grads = problem
    o = opt.SGD(learning_rate=0.1, rescale_grad=0.5, clip_gradient=0.2,
                wd=0.0)
    out = _run(o, w0, grads)
    w = w0.copy()
    for g in grads:
        w = w - 0.1 * np.clip(0.5 * g, -0.2, 0.2)
    np.testing.assert_allclose(out, w, rtol=1e-5)


def test_lr_scheduler_factor():
    from mxnet_tpu.optimizer.lr_scheduler import FactorScheduler
    # reference semantics: decay fires when num_update EXCEEDS count+step
    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(0) == 1.0
    assert s(10) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25


def test_lr_scheduler_cosine_warmup():
    from mxnet_tpu.optimizer.lr_scheduler import CosineScheduler
    s = CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.0,
                        warmup_steps=10)
    assert s(0) < s(9)                 # warming up
    assert s(10) == pytest.approx(1.0, rel=0.2)
    assert s(100) == pytest.approx(0.0, abs=1e-6)


def test_optimizer_registry_create():
    for name in ("sgd", "nag", "adam", "adamw", "adagrad", "adadelta",
                 "rmsprop", "ftrl", "signum", "lamb", "lars", "sgld"):
        o = opt.create(name, learning_rate=0.1)
        assert isinstance(o, opt.Optimizer)


def test_multi_precision_fp16_master_weights():
    o = opt.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    w = nd.ones((4,)).astype("float16")
    state = o.create_state_multi_precision(0, w)
    o.update_multi_precision(0, w, nd.ones((4,)).astype("float16"), state)
    assert str(w.data.dtype) == "float16"
    assert not np.allclose(w.asnumpy(), 1.0)


def test_trainer_states_roundtrip(tmp_path):
    from mxnet_tpu import gluon
    net = gluon.nn.Dense(3)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    x = nd.random.uniform(shape=(2, 4))
    from mxnet_tpu import autograd
    for _ in range(2):
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(2)
    f = str(tmp_path / "states")
    tr.save_states(f)
    tr2 = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 0.01})
    tr2.load_states(f)
    assert tr2._optimizer.num_update == tr._optimizer.num_update


def test_trainer_fused_group_update_parity():
    """gluon.Trainer's multi-tensor SGD fast path must match the
    per-param update bit-for-bit (reference multi_sgd_mom_update parity
    with sgd_mom_update)."""
    import numpy as np
    from mxnet_tpu import gluon, autograd, nd
    from mxnet_tpu.gluon import nn

    def build_and_train(disable_fused):
        np.random.seed(3)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
        net.initialize(init=mx.init.Constant(0.07))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9,
                            "wd": 0.01}, kvstore=None)
        if disable_fused:
            tr._fused_group_update = lambda *_: False
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        x = nd.array(np.random.RandomState(5).randn(6, 4)
                     .astype(np.float32))
        y = nd.array(np.array([0, 1, 0, 1, 1, 0], np.float32))
        for _ in range(4):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(6)
        # name-scope counters differ between builds; compare by order
        return [v.data().asnumpy()
                for _, v in sorted(net.collect_params().items())]

    fused = build_and_train(False)
    serial = build_and_train(True)
    for i, (f, s) in enumerate(zip(fused, serial)):
        np.testing.assert_allclose(f, s, rtol=1e-6, err_msg=str(i))


def test_clip_gradient_zero_freezes_update():
    """clip_gradient=0.0 clamps grads to zero (reference optimizer ops
    clip whenever clip_gradient >= 0; only negative disables). A zero
    clip must freeze the weight save for weight decay."""
    w = nd.array(np.array([1.0, -2.0, 3.0], np.float32))
    g = nd.array(np.array([10.0, -10.0, 5.0], np.float32))
    out = nd.sgd_update(w, g, lr=0.5, wd=0.0, clip_gradient=0.0)
    np.testing.assert_allclose(out.asnumpy(), [1.0, -2.0, 3.0], atol=1e-7)
    # negative still means disabled
    w2 = nd.array(np.array([1.0], np.float32))
    g2 = nd.array(np.array([2.0], np.float32))
    out2 = nd.sgd_update(w2, g2, lr=0.5, wd=0.0, clip_gradient=-1.0)
    np.testing.assert_allclose(out2.asnumpy(), [0.0], atol=1e-7)
    # multi-tensor path honors the same semantics
    w3 = nd.array(np.array([4.0], np.float32))
    g3 = nd.array(np.array([100.0], np.float32))
    nd.multi_sgd_update(w3, g3, lrs=[0.5], wds=[0.0],
                        clip_gradient=0.0, num_weights=1)
    np.testing.assert_allclose(w3.asnumpy(), [4.0], atol=1e-7)
