"""Gluon layer oracle vs torch.nn (SURVEY §4 check_consistency): copied
weights must reproduce torch outputs for the normalization/conv/embed
layer families, in both train and eval semantics where they differ."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from mxnet_tpu import nd, autograd
from mxnet_tpu.gluon import nn

RNG = np.random.RandomState(3)


def test_batchnorm_train_and_eval_match_torch():
    x = RNG.randn(4, 5, 6, 6).astype(np.float32)
    bn = nn.BatchNorm(in_channels=5, momentum=0.9, epsilon=1e-5)
    bn.initialize()
    tbn = torch.nn.BatchNorm2d(5, momentum=0.1, eps=1e-5)  # torch: 1-m
    g = RNG.rand(5).astype(np.float32) + 0.5
    b = RNG.randn(5).astype(np.float32)
    bn.gamma.set_data(nd.array(g))
    bn.beta.set_data(nd.array(b))
    with torch.no_grad():
        tbn.weight.copy_(torch.from_numpy(g))
        tbn.bias.copy_(torch.from_numpy(b))

    tbn.train()
    with autograd.record():                 # training mode: batch stats
        y = bn(nd.array(x))
    ty = tbn(torch.from_numpy(x))
    np.testing.assert_allclose(y.asnumpy(), ty.detach().numpy(),
                               rtol=1e-4, atol=1e-4)
    # running-stat conventions: momentum maps as mxnet m <-> torch 1-m;
    # torch accumulates the UNBIASED batch var while mxnet (reference
    # src/operator/nn/batch_norm.cc) accumulates the BIASED one — verify
    # each against its own convention from the same batch
    n = x.shape[0] * x.shape[2] * x.shape[3]
    bmean = x.mean(axis=(0, 2, 3))
    bvar = x.var(axis=(0, 2, 3))
    np.testing.assert_allclose(
        bn.running_mean.data().asnumpy(), 0.1 * bmean, rtol=1e-4,
        atol=1e-6)
    np.testing.assert_allclose(
        tbn.running_mean.numpy(), 0.1 * bmean, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(
        bn.running_var.data().asnumpy(), 0.9 + 0.1 * bvar, rtol=1e-4)
    np.testing.assert_allclose(
        tbn.running_var.numpy(), 0.9 + 0.1 * bvar * n / (n - 1),
        rtol=1e-4)

    # inference: each normalizes by its OWN running stats; check ours
    # against the closed form (torch's differs by the var convention)
    y_eval = bn(nd.array(x)).asnumpy()
    rm = bn.running_mean.data().asnumpy()
    rv = bn.running_var.data().asnumpy()
    want = ((x - rm[None, :, None, None])
            / np.sqrt(rv[None, :, None, None] + 1e-5)
            * g[None, :, None, None] + b[None, :, None, None])
    np.testing.assert_allclose(y_eval, want, rtol=1e-4, atol=1e-4)


def test_layernorm_and_groupnorm_match_torch():
    x = RNG.randn(4, 6, 5).astype(np.float32)
    ln = nn.LayerNorm(in_channels=5)
    ln.initialize()
    tln = torch.nn.LayerNorm(5)
    np.testing.assert_allclose(
        ln(nd.array(x)).asnumpy(),
        tln(torch.from_numpy(x)).detach().numpy(), rtol=1e-4, atol=1e-5)

    xg = RNG.randn(4, 6, 5, 5).astype(np.float32)
    gn = nn.GroupNorm(num_groups=3, in_channels=6)
    gn.initialize()
    tgn = torch.nn.GroupNorm(3, 6)
    np.testing.assert_allclose(
        gn(nd.array(xg)).asnumpy(),
        tgn(torch.from_numpy(xg)).detach().numpy(), rtol=1e-4, atol=1e-4)


def test_conv_transpose_matches_torch():
    x = RNG.randn(2, 3, 7, 7).astype(np.float32)
    w = RNG.randn(3, 4, 3, 3).astype(np.float32)   # (in, out, kH, kW)
    layer = nn.Conv2DTranspose(4, kernel_size=3, strides=2, padding=1,
                               output_padding=1, in_channels=3,
                               use_bias=False)
    layer.initialize()
    layer.weight.set_data(nd.array(w))
    want = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x), torch.from_numpy(w), stride=2, padding=1,
        output_padding=1).numpy()
    np.testing.assert_allclose(layer(nd.array(x)).asnumpy(), want,
                               rtol=1e-4, atol=1e-4)


def test_embedding_forward_and_grad_match_torch():
    W = RNG.randn(11, 7).astype(np.float32)
    idx = RNG.randint(0, 11, size=(4, 5))
    emb = nn.Embedding(11, 7)
    emb.initialize()
    emb.weight.set_data(nd.array(W))
    temb = torch.nn.Embedding(11, 7)
    with torch.no_grad():
        temb.weight.copy_(torch.from_numpy(W))

    xi = nd.array(idx.astype(np.float32))
    with autograd.record():
        y = emb(xi)
        loss = (y * y).sum()
    loss.backward()
    ti = torch.from_numpy(idx)
    ty = temb(ti)
    tloss = (ty * ty).sum()
    tloss.backward()
    np.testing.assert_allclose(y.asnumpy(), ty.detach().numpy(),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(emb.weight.grad().asnumpy(),
                               temb.weight.grad.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_dense_grads_match_torch():
    x = RNG.randn(3, 4).astype(np.float32)
    W = RNG.randn(5, 4).astype(np.float32)
    b = RNG.randn(5).astype(np.float32)
    d = nn.Dense(5, in_units=4)
    d.initialize()
    d.weight.set_data(nd.array(W))
    d.bias.set_data(nd.array(b))
    td = torch.nn.Linear(4, 5)
    with torch.no_grad():
        td.weight.copy_(torch.from_numpy(W))
        td.bias.copy_(torch.from_numpy(b))
    with autograd.record():
        loss = d(nd.array(x)).sum()
    loss.backward()
    tx = torch.from_numpy(x)
    td(tx).sum().backward()
    np.testing.assert_allclose(d.weight.grad().asnumpy(),
                               td.weight.grad.numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(d.bias.grad().asnumpy(),
                               td.bias.grad.numpy(), rtol=1e-5,
                               atol=1e-5)
