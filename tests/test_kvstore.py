"""KVStore semantics (reference: tests/python/unittest/test_kvstore.py +
nightly dist_sync_kvstore.py --gc-type 2bit for compression)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.kvstore.kvstore import GradientCompression

nd = mx.nd


def test_init_push_pull_single():
    kv = mx.kv.create("local")
    kv.init("a", nd.ones((4,)))
    kv.push("a", nd.ones((4,)) * 3)
    out = nd.zeros((4,))
    kv.pull("a", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)


def test_push_list_reduces():
    kv = mx.kv.create("device")
    kv.init(0, nd.zeros((2, 2)))
    kv.push(0, [nd.ones((2, 2)), nd.ones((2, 2)) * 2])
    out = nd.zeros((2, 2))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)


def test_list_keys():
    kv = mx.kv.create("local")
    kv.init(["x", "y"], [nd.ones((2,)), nd.ones((3,))])
    outs = [nd.zeros((2,)), nd.zeros((3,))]
    kv.pull(["x", "y"], out=outs)
    assert outs[0].shape == (2,)
    assert outs[1].shape == (3,)


def test_updater_applied_on_push():
    kv = mx.kv.create("local")
    kv.init(3, nd.ones((4, 4)))

    def update(key, grad, weight):
        weight -= 0.5 * grad

    kv._set_updater(update)
    kv.push(3, nd.ones((4, 4)))
    out = nd.zeros((4, 4))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.5)


def test_gradient_compression_roundtrip():
    gc = GradientCompression(threshold=0.5)
    g = np.array([0.7, -0.7, 0.1, -0.1, 2.0], np.float32)
    packed, shape = gc.compress("k", mx.nd.array(g).data)
    deq = np.asarray(gc.decompress(packed, shape))
    np.testing.assert_allclose(deq, [0.5, -0.5, 0.0, 0.0, 0.5])
    # error feedback: residual carries the truncated mass
    res = np.asarray(gc._residuals["k"])
    np.testing.assert_allclose(res, [0.2, -0.2, 0.1, -0.1, 1.5], atol=1e-6)
    # second step: residual alone pushes 1.5 -> +0.5 again
    packed2, _ = gc.compress("k", mx.nd.zeros((5,)).data)
    deq2 = np.asarray(gc.decompress(packed2, shape))
    assert deq2[4] == pytest.approx(0.5)


def test_gradient_compression_packing_is_4x():
    gc = GradientCompression(threshold=1.0)
    g = mx.nd.random.uniform(-2, 2, shape=(1024,)).data
    packed, _ = gc.compress("k", g)
    assert packed.dtype.name == "uint8"
    assert packed.shape == (256,)     # 4 codes per byte


def test_kvstore_with_compression():
    # compression applies to the cross-worker hop -> dist store only
    # (single-process dist still exercises the pack/unpack path)
    kv = mx.kv.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(0, nd.zeros((4,)))
    kv.push(0, nd.array([1.0, -1.0, 0.2, 0.0]))
    out = nd.zeros((4,))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])


def test_optimizer_on_kvstore():
    kv = mx.kv.create("local")
    kv.init(0, nd.ones((4,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    kv.push(0, nd.ones((4,)))
    out = nd.zeros((4,))
    kv.pull(0, out=out)
    assert not np.allclose(out.asnumpy(), 1.0)     # weight moved


def test_int8_compression_roundtrip_and_feedback():
    """EQuARX-style blockwise int8 wire quantization (PAPERS.md row 9):
    value-proportional error, ~4x wire reduction, error feedback."""
    from mxnet_tpu.kvstore.kvstore import Int8GradientCompression
    gc = Int8GradientCompression()
    rng = np.random.RandomState(0)
    g = mx.nd.array(rng.randn(1000).astype(np.float32) * 0.01).data
    packed, shape = gc.compress("k", g)
    assert packed.dtype.name == "uint8"
    # 1000 values -> 4 blocks of 256: 1024 code bytes + 16 scale bytes
    assert packed.shape == (1040,)
    deq = np.asarray(gc.decompress(packed, shape))
    scale_bound = np.abs(np.asarray(g)).max() / 127.0
    assert np.abs(deq - np.asarray(g)).max() <= scale_bound
    # error feedback: the running mean of dequantized grads converges far
    # below one quantization step
    gc2 = Int8GradientCompression()
    acc = np.zeros(1000, np.float32)
    for _ in range(30):
        p, s = gc2.compress("k", g)
        acc += np.asarray(gc2.decompress(p, s))
    assert np.abs(acc / 30 - np.asarray(g)).max() < scale_bound / 20
    # non-multiple-of-block sizes roundtrip
    g3 = mx.nd.array(rng.randn(777).astype(np.float32)).data
    p3, s3 = gc.compress("x", g3)
    d3 = np.asarray(gc.decompress(p3, s3))
    assert d3.shape == (777,)
    assert np.abs(d3 - np.asarray(g3)).max() <= \
        np.abs(np.asarray(g3)).max() / 127.0


def test_kvstore_with_int8_compression():
    kv = mx.kv.create("dist_sync")
    kv.set_gradient_compression({"type": "int8"})
    kv.init(1, nd.zeros((600,)))
    g = np.linspace(-1, 1, 600).astype(np.float32)
    kv.push(1, nd.array(g))
    out = nd.zeros((600,))
    kv.pull(1, out=out)
    np.testing.assert_allclose(out.asnumpy(), g, atol=1.0 / 127.0)


def test_compression_rejects_unknown_params():
    kv = mx.kv.create("dist_sync")
    with pytest.raises(mx.MXNetError):
        kv.set_gradient_compression({"type": "int8", "threshold": 0.1})
    with pytest.raises(mx.MXNetError):
        kv.set_gradient_compression({"type": "2bit", "block": 64})
