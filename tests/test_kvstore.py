"""KVStore semantics (reference: tests/python/unittest/test_kvstore.py +
nightly dist_sync_kvstore.py --gc-type 2bit for compression)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.kvstore.kvstore import GradientCompression

nd = mx.nd


def test_init_push_pull_single():
    kv = mx.kv.create("local")
    kv.init("a", nd.ones((4,)))
    kv.push("a", nd.ones((4,)) * 3)
    out = nd.zeros((4,))
    kv.pull("a", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)


def test_push_list_reduces():
    kv = mx.kv.create("device")
    kv.init(0, nd.zeros((2, 2)))
    kv.push(0, [nd.ones((2, 2)), nd.ones((2, 2)) * 2])
    out = nd.zeros((2, 2))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)


def test_list_keys():
    kv = mx.kv.create("local")
    kv.init(["x", "y"], [nd.ones((2,)), nd.ones((3,))])
    outs = [nd.zeros((2,)), nd.zeros((3,))]
    kv.pull(["x", "y"], out=outs)
    assert outs[0].shape == (2,)
    assert outs[1].shape == (3,)


def test_updater_applied_on_push():
    kv = mx.kv.create("local")
    kv.init(3, nd.ones((4, 4)))

    def update(key, grad, weight):
        weight -= 0.5 * grad

    kv._set_updater(update)
    kv.push(3, nd.ones((4, 4)))
    out = nd.zeros((4, 4))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 0.5)


def test_gradient_compression_roundtrip():
    gc = GradientCompression(threshold=0.5)
    g = np.array([0.7, -0.7, 0.1, -0.1, 2.0], np.float32)
    packed, shape = gc.compress("k", mx.nd.array(g).data)
    deq = np.asarray(gc.decompress(packed, shape))
    np.testing.assert_allclose(deq, [0.5, -0.5, 0.0, 0.0, 0.5])
    # error feedback: residual carries the truncated mass
    res = np.asarray(gc._residuals["k"])
    np.testing.assert_allclose(res, [0.2, -0.2, 0.1, -0.1, 1.5], atol=1e-6)
    # second step: residual alone pushes 1.5 -> +0.5 again
    packed2, _ = gc.compress("k", mx.nd.zeros((5,)).data)
    deq2 = np.asarray(gc.decompress(packed2, shape))
    assert deq2[4] == pytest.approx(0.5)


def test_gradient_compression_packing_is_4x():
    gc = GradientCompression(threshold=1.0)
    g = mx.nd.random.uniform(-2, 2, shape=(1024,)).data
    packed, _ = gc.compress("k", g)
    assert packed.dtype.name == "uint8"
    assert packed.shape == (256,)     # 4 codes per byte


def test_kvstore_with_compression():
    # compression applies to the cross-worker hop -> dist store only
    # (single-process dist still exercises the pack/unpack path)
    kv = mx.kv.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(0, nd.zeros((4,)))
    kv.push(0, nd.array([1.0, -1.0, 0.2, 0.0]))
    out = nd.zeros((4,))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])


def test_optimizer_on_kvstore():
    kv = mx.kv.create("local")
    kv.init(0, nd.ones((4,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    kv.push(0, nd.ones((4,)))
    out = nd.zeros((4,))
    kv.pull(0, out=out)
    assert not np.allclose(out.asnumpy(), 1.0)     # weight moved


def test_int8_compression_roundtrip_and_feedback():
    """EQuARX-style blockwise int8 wire quantization (PAPERS.md row 9):
    value-proportional error, ~4x wire reduction, error feedback."""
    from mxnet_tpu.kvstore.kvstore import Int8GradientCompression
    gc = Int8GradientCompression()
    rng = np.random.RandomState(0)
    g = mx.nd.array(rng.randn(1000).astype(np.float32) * 0.01).data
    packed, shape = gc.compress("k", g)
    assert packed.dtype.name == "uint8"
    # 1000 values -> 4 blocks of 256: 1024 code bytes + 16 scale bytes
    assert packed.shape == (1040,)
    deq = np.asarray(gc.decompress(packed, shape))
    scale_bound = np.abs(np.asarray(g)).max() / 127.0
    assert np.abs(deq - np.asarray(g)).max() <= scale_bound
    # error feedback: the running mean of dequantized grads converges far
    # below one quantization step
    gc2 = Int8GradientCompression()
    acc = np.zeros(1000, np.float32)
    for _ in range(30):
        p, s = gc2.compress("k", g)
        acc += np.asarray(gc2.decompress(p, s))
    assert np.abs(acc / 30 - np.asarray(g)).max() < scale_bound / 20
    # non-multiple-of-block sizes roundtrip
    g3 = mx.nd.array(rng.randn(777).astype(np.float32)).data
    p3, s3 = gc.compress("x", g3)
    d3 = np.asarray(gc.decompress(p3, s3))
    assert d3.shape == (777,)
    assert np.abs(d3 - np.asarray(g3)).max() <= \
        np.abs(np.asarray(g3)).max() / 127.0


def test_kvstore_with_int8_compression():
    kv = mx.kv.create("dist_sync")
    kv.set_gradient_compression({"type": "int8"})
    kv.init(1, nd.zeros((600,)))
    g = np.linspace(-1, 1, 600).astype(np.float32)
    kv.push(1, nd.array(g))
    out = nd.zeros((600,))
    kv.pull(1, out=out)
    np.testing.assert_allclose(out.asnumpy(), g, atol=1.0 / 127.0)


def test_compression_rejects_unknown_params():
    kv = mx.kv.create("dist_sync")
    with pytest.raises(mx.MXNetError):
        kv.set_gradient_compression({"type": "int8", "threshold": 0.1})
    with pytest.raises(mx.MXNetError):
        kv.set_gradient_compression({"type": "2bit", "block": 64})


def _mesh8(axis="dp"):
    import jax
    devs = np.array(jax.devices()[:8])
    from jax.sharding import Mesh
    return Mesh(devs, (axis,))


def test_tpu_sync_traced_push_lowers_to_psum():
    """VERDICT r3 #9: a traced push through the tpu_sync facade must stay
    in-graph as a psum over the mesh data axis — assert on the jaxpr and
    on executed numerics (every shard sees the cross-device sum)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.ndarray.ndarray import NDArray

    mesh = _mesh8()
    kv = mx.kv.create("tpu_sync")
    kv.init(3, nd.zeros((4,)))

    def step(g):
        gn = NDArray(g[0])          # shard-local (1,4) -> (4,)
        kv.push(3, gn)
        out = NDArray(jnp.zeros((4,), jnp.float32))
        kv.pull(3, out=out)
        return out.data[None, :]

    f = shard_map(step, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    x = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
    jaxpr = str(jax.make_jaxpr(f)(x))
    assert "psum" in jaxpr
    y = np.asarray(jax.jit(f)(x))
    expect = np.asarray(x).sum(axis=0)
    for shard in y:
        np.testing.assert_allclose(shard, expect, rtol=1e-6)


def test_dist_tpu_sync_traced_push_stays_in_graph():
    """VERDICT r3 #4b: pushpull inside a jitted step must not take the
    host-mediated bucketed-allreduce (device_put/D2H per bucket). Tracing
    succeeding is itself the no-host-sync proof (np.asarray on a tracer
    raises); also assert the collective is in the lowered jaxpr."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.ndarray.ndarray import NDArray

    mesh = _mesh8()
    kv = mx.kv.create("dist_tpu_sync")
    kv.init(7, nd.zeros((2,)))

    def step(g):
        gn = NDArray(g[0])
        kv.pushpull(7, gn, out=gn)
        return gn.data[None, :]

    f = shard_map(step, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    x = jnp.ones((8, 2), jnp.float32)
    jaxpr = str(jax.make_jaxpr(f)(x))
    assert "psum" in jaxpr
    y = np.asarray(jax.jit(f)(x))
    np.testing.assert_allclose(y, np.full((8, 2), 8.0), rtol=1e-6)


def test_tpu_sync_traced_push_rejects_updater():
    from mxnet_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp
    from mxnet_tpu.ndarray.ndarray import NDArray

    mesh = _mesh8()
    kv = mx.kv.create("tpu_sync")
    kv.init(1, nd.zeros((2,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))

    def step(g):
        gn = NDArray(g[0])
        kv.push(1, gn)
        return g

    f = shard_map(step, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    with pytest.raises(mx.MXNetError, match="update-on-kvstore"):
        import jax
        jax.make_jaxpr(f)(jnp.ones((8, 2), jnp.float32))


def test_tpu_sync_traced_mixed_pull_and_stale_scrub():
    """Review findings: mixed traced/eager pulls route per key; stale
    tracers from an aborted trace never leak into eager pulls."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.ndarray.ndarray import NDArray

    mesh = _mesh8()
    kv = mx.kv.create("tpu_sync")
    kv.init(1, nd.array([10.0, 20.0]))
    kv.init(2, nd.array([5.0, 6.0]))

    def step(g):
        gn = NDArray(g[0])
        kv.push(1, gn)
        o1 = NDArray(jnp.zeros((2,), jnp.float32))
        o2 = nd.zeros((2,))
        kv.pull([1, 2], out=[o1, o2])    # key 2 was never pushed traced
        return (o1.data + o2.data)[None, :]

    f = shard_map(step, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    y = np.asarray(jax.jit(f)(jnp.ones((8, 2), jnp.float32)))
    np.testing.assert_allclose(y, np.full((8, 2), 8.0) + [5.0, 6.0])

    # aborted trace: push happens, pull never does -> eager pull must
    # return the stored value, not the dead tracer
    def bad_step(g):
        kv.push(1, NDArray(g[0]))
        raise ValueError("abort after push")

    fb = shard_map(bad_step, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    with pytest.raises(ValueError):
        jax.make_jaxpr(fb)(jnp.ones((8, 2), jnp.float32))
    out = nd.zeros((2,))
    kv.pull(1, out=out)
    np.testing.assert_allclose(out.asnumpy(), [10.0, 20.0])


def test_tpu_sync_traced_push_guards():
    """Uninitialized keys and unbound axis names fail fast with guidance."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel._compat import shard_map
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.ndarray.ndarray import NDArray

    kv = mx.kv.create("tpu_sync")
    kv.init(0, nd.zeros((2,)))
    mesh = _mesh8()

    def push99(g):
        kv.push(99, NDArray(g[0]))
        return g

    f = shard_map(push99, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    with pytest.raises(mx.MXNetError, match="not initialized"):
        jax.make_jaxpr(f)(jnp.ones((8, 2), jnp.float32))

    mesh_model = _mesh8(axis="model")    # no 'dp' axis in scope

    def push0(g):
        kv.push(0, NDArray(g[0]))
        return g

    fm = shard_map(push0, mesh=mesh_model,
                   in_specs=P("model"), out_specs=P("model"))
    with pytest.raises(mx.MXNetError, match="set_data_axis"):
        jax.make_jaxpr(fm)(jnp.ones((8, 2), jnp.float32))


def test_horovod_byteps_adapter_facades():
    """Reference >=1.6 kvstore/horovod.py + byteps.py adapters (VERDICT r3
    missing #5): create() accepts the names, push/pull keep allreduce
    semantics, server-side optimizer is refused like the reference."""
    for name in ("horovod", "byteps"):
        kv = mx.kv.create(name)
        assert kv.type == name
        assert kv.rank == 0 and kv.num_workers == 1
        kv.init(0, nd.zeros((3,)))
        v = nd.array([1.0, 2.0, 3.0])
        kv.pushpull(0, v, out=v)
        np.testing.assert_allclose(v.asnumpy(), [1.0, 2.0, 3.0])
        with pytest.raises(mx.MXNetError, match="server-side"):
            kv.set_optimizer(mx.optimizer.SGD())


def test_interval_sampler_and_send_command():
    """gluon.contrib.data.IntervalSampler + KVStore.send_command_to_servers
    (reference contrib/data/sampler.py, kvstore.py controller messages)."""
    from mxnet_tpu.gluon.contrib.data import IntervalSampler
    s = IntervalSampler(10, 3)
    order = list(s)
    assert sorted(order) == list(range(10)) and len(s) == 10
    assert order[:4] == [0, 3, 6, 9]
    s2 = IntervalSampler(10, 3, rollover=False)
    assert list(s2) == [0, 3, 6, 9] and len(s2) == 4
    # serverless stores: documented no-op
    mx.kv.create("local").send_command_to_servers(0, "anything")
