"""Pretrained zoo path + NDARRAY_V2 golden checkpoint (VERDICT r3 #7).

Reference: python/mxnet/gluon/model_zoo/model_store.py (get_model_file),
src/ndarray/ndarray.cc NDArray::Save/Load (the .params container)."""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.model_zoo.model_store import get_model_file
from mxnet_tpu.ndarray.utils import load, save_legacy

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "golden_ndarray_v2.params")


def test_golden_ndarray_v2_fixture_loads_exactly():
    """The committed .params blob is byte-genuine NDARRAY_V2: verify the
    container layout by hand, then the reader's exact values."""
    blob = open(FIXTURE, "rb").read()
    assert struct.unpack_from("<Q", blob, 0)[0] == 0x112      # file magic
    assert struct.unpack_from("<Q", blob, 16)[0] == 4         # count
    assert struct.unpack_from("<I", blob, 24)[0] == 0xF993FAC9  # NDARRAY_V2
    # dense stype is 0 (kDefaultStorage) in the reference enum —
    # kUndefinedStorage (-1) never appears in genuine reference files
    assert struct.unpack_from("<i", blob, 28)[0] == 0

    d = load(FIXTURE)
    assert sorted(d) == ["arg:dense0_bias", "arg:dense0_weight",
                         "arg:embed_int", "aux:batchnorm0_running_mean"]
    rng = np.random.RandomState(42)
    np.testing.assert_array_equal(d["arg:dense0_weight"].asnumpy(),
                                  rng.randn(4, 3).astype(np.float32))
    np.testing.assert_array_equal(d["arg:dense0_bias"].asnumpy(),
                                  rng.randn(4).astype(np.float32))
    rm = d["aux:batchnorm0_running_mean"]
    np.testing.assert_array_equal(rm.asnumpy(),
                                  rng.rand(4).astype(np.float16))
    assert rm.dtype == np.float16
    ei = d["arg:embed_int"]
    np.testing.assert_array_equal(ei.asnumpy(),
                                  rng.randint(-5, 5, (2, 2)))
    assert ei.dtype == np.int32


def test_legacy_writer_reader_roundtrip(tmp_path):
    d = {"w": nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)),
         "b": nd.array(np.array([1.0, 2.0], np.float16), dtype="float16"),
         "i": nd.array([1, 2, 3], dtype="int32")}
    p = str(tmp_path / "rt.params")
    save_legacy(p, d)
    back = load(p)
    for k in d:
        np.testing.assert_array_equal(back[k].asnumpy(), d[k].asnumpy())
        assert back[k].dtype == d[k].dtype
    # unnamed list form
    p2 = str(tmp_path / "rt2.params")
    save_legacy(p2, [nd.array([1.0])])
    lst = load(p2)
    assert isinstance(lst, list) and len(lst) == 1
    with pytest.raises(mx.MXNetError):
        save_legacy(str(tmp_path / "bad.params"),
                    {"x": nd.array([1.0], dtype="bfloat16")})


def test_get_model_file_resolution(tmp_path):
    root = tmp_path / "store"
    root.mkdir()
    (root / "resnet18_v1.params").write_bytes(b"x")
    assert get_model_file("resnet18_v1", str(root)).endswith(
        "resnet18_v1.params")
    # reference hashed naming also resolves
    (root / "alexnet-44335d1f.params").write_bytes(b"x")
    assert get_model_file("alexnet", str(root)).endswith(
        "alexnet-44335d1f.params")
    with pytest.raises(mx.MXNetError, match="model store"):
        get_model_file("vgg16", str(root))
    # env-var root
    os.environ["MXTPU_MODEL_STORE"] = str(root)
    try:
        assert get_model_file("alexnet").endswith(".params")
    finally:
        del os.environ["MXTPU_MODEL_STORE"]


@pytest.mark.slow   # slow-marked (ISSUE 18 tier-1 headroom): the store
# registry/format/eviction tests above keep the load path tier-1; this
# is the end-to-end pretrained one-liner over both container formats
def test_pretrained_one_liner_offline(tmp_path):
    """get_model(name, pretrained=True, root=...) — the one-line load.
    Covers both container formats in the store: native save_parameters
    output AND a reference-era (legacy-written) NDARRAY_V2 file."""
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(1, 3, 32, 32).astype(np.float32))

    src = vision.squeezenet1_0(classes=7)
    src.initialize()
    y_src = src(x)
    root = tmp_path / "models"
    root.mkdir()
    src.save_parameters(str(root / "squeezenet1.0.params"))

    net = vision.get_model("squeezenet1.0", pretrained=True, root=str(root),
                           classes=7)
    np.testing.assert_allclose(net(x).asnumpy(), y_src.asnumpy(),
                               rtol=1e-5, atol=1e-6)

    # legacy-format store entry: same params re-written as NDARRAY_V2
    # with the structural arg:/aux: names reference checkpoints carry
    legacy_dict = {f"arg:{k}": p.data()
                   for k, p in src._collect_params_with_prefix().items()}
    save_legacy(str(root / "squeezenet1.0-deadbeef.params"), legacy_dict)
    os.remove(root / "squeezenet1.0.params")
    net2 = vision.get_model("squeezenet1.0", pretrained=True,
                            root=str(root), classes=7)
    np.testing.assert_allclose(net2(x).asnumpy(), y_src.asnumpy(),
                               rtol=1e-5, atol=1e-6)

    with pytest.raises(mx.MXNetError, match="model store"):
        vision.get_model("vgg11", pretrained=True, root=str(root))
