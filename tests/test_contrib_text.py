"""mx.contrib.text parity tests (reference python/mxnet/contrib/text/ —
vocab.py Vocabulary, embedding.py CustomEmbedding/CompositeEmbedding,
utils.py count_tokens_from_str)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text


def _vec_file():
    path = os.path.join(tempfile.mkdtemp(), "vec.txt")
    with open(path, "w") as f:
        f.write("hello 1.0 0.0\nworld 0.9 0.1\nfoo 0.0 1.0\n")
    return path


def test_count_tokens_and_vocabulary():
    c = text.count_tokens_from_str("a b b c c c\nd a")
    assert c["c"] == 3 and c["a"] == 2 and c["d"] == 1
    v = text.Vocabulary(c, min_freq=2, reserved_tokens=["<pad>"])
    # <unk>, <pad>, then by (-freq, token): c, a, b
    assert v.idx_to_token == ["<unk>", "<pad>", "c", "a", "b"]
    assert v.to_indices(["c", "never-seen"]) == [2, 0]
    assert v.to_tokens([2, 0]) == ["c", "<unk>"]
    with pytest.raises(mx.MXNetError):
        v.to_tokens(99)
    with pytest.raises(mx.MXNetError):
        text.Vocabulary(c, reserved_tokens=["<unk>"])


def test_custom_embedding_lookup_update_similarity():
    emb = text.CustomEmbedding(_vec_file())
    assert emb.vec_len == 2 and len(emb) == 4
    vecs = emb.get_vecs_by_tokens(["hello", "missing"])
    np.testing.assert_allclose(vecs.asnumpy(), [[1.0, 0.0], [0.0, 0.0]])
    assert emb.most_similar("hello", k=1)[0][0] == "world"
    emb.update_token_vectors("foo", mx.nd.array([[0.5, 0.5]]))
    np.testing.assert_allclose(emb.get_vecs_by_tokens("foo").asnumpy(),
                               [0.5, 0.5])
    with pytest.raises(mx.MXNetError):
        emb.update_token_vectors("missing", mx.nd.array([[1.0, 1.0]]))


def test_embedding_with_vocabulary_and_composite():
    c = text.count_tokens_from_str("hello world hello unseen")
    v = text.Vocabulary(c)
    emb = text.CustomEmbedding(_vec_file(), vocabulary=v)
    assert len(emb) == len(v)
    # vocab token not in the file gets the unknown vector
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("unseen").asnumpy(), [0.0, 0.0])
    comp = text.CompositeEmbedding(v, [emb, emb])
    assert comp.idx_to_vec.shape == (len(v), 4)


def test_pretrained_downloads_gated():
    with pytest.raises(mx.MXNetError):
        text.create("glove")
    with pytest.raises(mx.MXNetError):
        text.get_pretrained_file_names()


def test_fasttext_style_header_and_whitespace():
    path = os.path.join(tempfile.mkdtemp(), "ft.vec")
    with open(path, "w") as f:
        f.write("2 3\nhello 1 0 0 \nworld 0 1 0\n")   # header + trailing ws
    emb = text.CustomEmbedding(path)
    assert emb.vec_len == 3 and len(emb) == 3
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [1.0, 0.0, 0.0])


def test_one_dim_embedding_integer_token_not_eaten_as_header():
    """A legit 1-d embedding whose first token is an integer string must not
    be dropped by the fastText header heuristic (advisor round-3 finding)."""
    path = os.path.join(tempfile.mkdtemp(), "one.vec")
    with open(path, "w") as f:
        f.write("7 5\nfoo 2\nbar 3\n")   # '7' is a token, not a count
    emb = text.CustomEmbedding(path)
    assert emb.vec_len == 1 and len(emb) == 4   # unk + 3 tokens
    np.testing.assert_allclose(emb.get_vecs_by_tokens("7").asnumpy(), [5.0])
    # a real header (dim agrees with following rows, dim > 1) is still dropped
    path2 = os.path.join(tempfile.mkdtemp(), "hdr.vec")
    with open(path2, "w") as f:
        f.write("2 2\na 1 0\nb 0 1\n")
    emb2 = text.CustomEmbedding(path2)
    assert emb2.vec_len == 2 and len(emb2) == 3
    # a real header on a 1-d file: count field matches the data rows
    path3 = os.path.join(tempfile.mkdtemp(), "hdr1d.vec")
    with open(path3, "w") as f:
        f.write("3 1\na 5\nb 6\nc 7\n")
    emb3 = text.CustomEmbedding(path3)
    assert emb3.vec_len == 1 and len(emb3) == 4   # unk + a,b,c; '3' dropped
