"""Disaggregated prefill/decode serving (ISSUE 18, tentpole B).

One shared ``PagedKVCache`` behind PREFILL-role and DECODE-role
replicas: a prefill replica fills a request's blocks, then OWNERSHIP
moves to a decode replica through the pool's CoW refcounts —
adopt-then-release, so a crash between the two sides strands nothing
and duplicates nothing (typed :class:`HandoffError` on every protocol
violation).  The acceptance bar is BITWISE: the disaggregated fleet
must produce exactly the token streams of a solo combined-role
replica, with zero compiles after warmup and a leak-clean shared pool.

Runs on the simulated 8-device CPU mesh (tests/conftest.py).
"""
from __future__ import annotations

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError, NotSupportedError
from mxnet_tpu.gluon.model_zoo.nlp.llama import (LlamaConfig,
                                                 LlamaForCausalLM)
from mxnet_tpu.serving import (ContinuousBatcher, HandoffError,
                               InferenceEngine, Request, Router)

_STATE = {}


def _net():
    if "net" not in _STATE:
        cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=4, num_kv_heads=2,
                          intermediate_size=64, max_seq_len=64,
                          tie_embeddings=True)
        net = LlamaForCausalLM(cfg)
        net.initialize()
        net(mx.nd.array(np.zeros((1, 8), np.int32)))
        net.hybridize()
        _STATE["net"] = net
    return _STATE["net"]


# ONE compile cache for the whole module: every router/solo engine
# below shares it (signatures key on config + mesh, so layouts never
# collide), which keeps the file's compile bill to one warmup per
# distinct graph family
_CC = {}


def _factory(compile_cache, kv_cache=None, **kw):
    base = dict(max_batch=2, block_size=8, num_blocks=32,
                max_context=32)
    base.update(kw)
    return InferenceEngine(_net(), compile_cache=_CC,
                           kv_cache=kv_cache, **base)


def _prompts(n, seed=0):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(0, 64, (3 + i % 5,))) for i in range(n)]


def _solo_streams(prompts, **kw):
    """The combined-role reference streams, one solo batcher."""
    solo = ContinuousBatcher(_factory({}, **kw).warmup())
    reqs = [solo.submit(Request(list(p), max_new_tokens=4))
            for p in prompts]
    solo.run()
    return [list(r.generated) for r in reqs]


def _fleet():
    """One 2-replica disaggregated run, shared across the read-only
    assertions below (the fleet is deterministic: build once)."""
    if "fleet" not in _STATE:
        prompts = _prompts(7)
        refs = _solo_streams(prompts)
        router = Router(_factory, replicas=2, disaggregated=True)
        reqs = [Request(list(p), max_new_tokens=4) for p in prompts]
        for r in reqs:
            router.submit(r)
        router.drive()
        _STATE["fleet"] = (router, reqs, refs)
    return _STATE["fleet"]


def test_disagg_outputs_bitwise_solo_and_leak_clean():
    router, reqs, refs = _fleet()
    assert [list(r.generated) for r in reqs] == refs
    st = router.stats()
    assert st["disaggregated"] is True
    assert st["handoffs"] == len(reqs)   # every request crossed over
    assert st["requeues"] == 0
    assert st["compiles_after_warmup"] == 0
    # every slot released on both sides: the shared pool is empty
    router._shared_cache.check_leaks(holders=0)


def test_disagg_roles_and_shared_pool_in_manifest():
    router, _reqs, _refs = _fleet()
    man = router.manifest()
    assert man["disaggregated"] is True
    roles = {r["rid"]: r["role"] for r in man["replicas"]}
    assert roles == {0: "prefill", 1: "decode"}
    assert all(r["cache_shared"] for r in man["replicas"])
    # ONE pool object behind every replica
    caches = {id(rep.engine.cache) for rep in router.replicas}
    assert len(caches) == 1


def test_disagg_per_pool_occupancy_measured():
    router, _reqs, _refs = _fleet()
    st = router.stats()
    assert 0.0 < st["prefill_pool_occupancy"] <= 1.0
    assert 0.0 < st["decode_pool_occupancy"] <= 1.0
    roles = {r["rid"]: r["role"] for r in router.manifest()["replicas"]}
    for pr in st["per_replica"]:
        assert pr["role"] == roles[pr["rid"]]


def test_disagg_decode_replicas_never_admit():
    router, _reqs, _refs = _fleet()
    # submits landed only on the prefill replica; handoffs moved them
    assert all(rep.role == "prefill" or not rep.batcher.queue
               for rep in router.replicas)
    prefill_rep = router.replicas[0]
    decode_rep = router.replicas[1]
    assert len(decode_rep.batcher.finished) == 7
    assert not prefill_rep.batcher.handoff_ready


def test_disagg_threaded_start_typed_rejection():
    router, _reqs, _refs = _fleet()
    with pytest.raises(NotSupportedError):
        router.start()


def test_handoff_protocol_violations_are_typed():
    """Every way to break adopt-then-release raises HandoffError."""
    eng = _factory({}).warmup()
    # adopt on a non-decode role
    b = ContinuousBatcher(eng, role="combined")
    with pytest.raises(HandoffError):
        b.adopt_handoff(Request([1, 2], 2), [0], 2)
    # release-before-adopt: the prefill side may not drop its hold
    # until the decode side holds every block (refcount >= 2)
    pre = ContinuousBatcher(eng, slot_ns=0, role="prefill")
    req = pre.submit(Request([1, 2, 3], max_new_tokens=4))
    pre.step()
    assert pre.handoff_ready
    slot, _req = pre.handoff_ready[0]
    with pytest.raises(HandoffError):
        pre.complete_handoff(slot)
    eng.release(slot)
    pre.handoff_ready.clear()
    eng.cache.check_leaks(holders=0)


def test_disagg_factory_must_share_pool():
    """An engine_factory that ignores its kv_cache argument builds
    per-replica pools — the handoff protocol is impossible; typed
    rejection at construction."""
    def bad_factory(compile_cache, kv_cache=None):
        return _factory(compile_cache, kv_cache=None)
    with pytest.raises(HandoffError):
        Router(bad_factory, replicas=2, disaggregated=True)


def test_disagg_roundrobin_roles_and_pool_scaling():
    """Even rids prefill, odd rids decode; add_replica(role=...) grows
    the named pool and bare add_replica balances the smaller one."""
    router, _reqs, _refs = _fleet()
    rep = router.add_replica(role="decode")
    assert rep.role == "decode"
    rep2 = router.add_replica()   # prefill pool is now the smaller
    assert rep2.role == "prefill"
    # a combined fleet refuses role'd growth
    plain = Router(_factory, replicas=1)
    with pytest.raises(MXNetError):
        plain.add_replica(role="prefill")
    # never drain the last replica of a role
    small = Router(_factory, replicas=2, disaggregated=True)
    with pytest.raises(MXNetError):
        small.drain_replica(1)


def test_disagg_env_knob_default_inert(monkeypatch):
    """MXTPU_SERVE_DISAGG unset: the router is exactly the combined
    fleet (no roles, per-replica pools); set: disaggregated without
    code changes."""
    monkeypatch.delenv("MXTPU_SERVE_DISAGG", raising=False)
    plain = Router(_factory, replicas=2)
    assert plain.disaggregated is False
    assert all(r.role == "combined" for r in plain.replicas)
    assert len({id(r.engine.cache) for r in plain.replicas}) == 2
    monkeypatch.setenv("MXTPU_SERVE_DISAGG", "1")
    dis = Router(_factory, replicas=2)
    assert dis.disaggregated is True
    assert [r.role for r in dis.replicas] == ["prefill", "decode"]


def test_autoscaler_scales_pools_independently():
    """serving:prefill rules grow the prefill pool on TTFT pressure,
    serving:decode rules the decode pool on TPOT pressure — each with
    its own cooldown; a pool rule against a combined fleet is inert."""
    from mxnet_tpu.elastic import (Autoscaler, ScalingPolicy,
                                   ScalingRule)
    from mxnet_tpu.testing import faults
    clock = faults.FakeClock()
    router = Router(_factory, replicas=2, disaggregated=True)
    scaler = Autoscaler(
        ScalingPolicy([
            ScalingRule("serving.prefill.ttft_ms", high=100.0,
                        domain="serving:prefill", window_s=0.0),
            ScalingRule("serving.decode.tpot_ms", high=50.0,
                        domain="serving:decode", window_s=0.0),
        ], cooldown_s=0.0, max_replicas=3),
        router=router, now=clock)
    d = scaler.tick(signals={"serving.prefill.ttft_ms": 999.0,
                             "serving.decode.tpot_ms": 1.0})
    assert [x["domain"] for x in d] == ["serving:prefill"]
    assert router.replicas[-1].role == "prefill"
    clock.advance(1.0)
    d = scaler.tick(signals={"serving.prefill.ttft_ms": 1.0,
                             "serving.decode.tpot_ms": 999.0})
    assert [x["domain"] for x in d] == ["serving:decode"]
    assert router.replicas[-1].role == "decode"
    # pool-scoped rule against a combined fleet: inert bounds-skip
    plain = Router(_factory, replicas=1)
    s2 = Autoscaler(
        ScalingPolicy([ScalingRule("serving.prefill.ttft_ms",
                                   high=100.0,
                                   domain="serving:prefill",
                                   window_s=0.0)], cooldown_s=0.0),
        router=plain, now=clock)
    assert s2.tick(signals={"serving.prefill.ttft_ms": 999.0}) == []
    assert s2.skipped["bounds"] == 1


@pytest.mark.slow   # composition gate; the chaos serving scenario
# (tpu_queue_runner --chaos serving) drives spec-decode fleets per run
def test_disagg_composes_with_spec_decode():
    """MXTPU_SPEC_DECODE on the disaggregated fleet: the decode pool
    drafts+verifies, outputs stay bitwise the PLAIN solo streams."""
    prompts = _prompts(5, seed=4)
    refs = _solo_streams(prompts)

    def spec_factory(compile_cache, kv_cache=None):
        return _factory(compile_cache, kv_cache=kv_cache,
                        spec_decode=True, spec_k=2)

    router = Router(spec_factory, replicas=2, disaggregated=True)
    reqs = [Request(list(p), max_new_tokens=4) for p in prompts]
    for r in reqs:
        router.submit(r)
    router.drive()
    assert [list(r.generated) for r in reqs] == refs
    assert router.stats()["compiles_after_warmup"] == 0
    router._shared_cache.check_leaks(holders=0)


@pytest.mark.slow   # also tpu_queue_runner --chaos disagg
def test_chaos_prefill_replica_killed_mid_handoff():
    """The ISSUE 18 acceptance gate: a prefill replica killed BETWEEN
    "prefill finished" and "decode adopted" — zero lost, zero
    duplicated, outputs bitwise solo, shared pool leak-clean."""
    from mxnet_tpu.testing.chaos import run_disagg_scenario
    r = run_disagg_scenario()
    assert r["ok"], r
    assert r["requeues"] >= 1 and r["handoffs"] >= 1


@pytest.mark.slow   # also tpu_queue_runner --chaos disagg
def test_chaos_decode_replica_killed_at_boundary():
    """Decode-pool death: adopted requests requeue through a fresh
    prefill, still exactly once and bitwise solo."""
    from mxnet_tpu.testing.chaos import run_disagg_scenario
    r = run_disagg_scenario(kill_rid=1, kill_point="step", kill_at=3)
    assert r["ok"], r
