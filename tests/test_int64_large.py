"""Large-tensor (int64) semantics — the small-memory equivalent of the
reference's tests/nightly/test_large_array.py: we cannot allocate >2^31
elements here, but every *index-arithmetic* path that overflows int32 can
be exercised with scalars/coordinates beyond 2^31 (reference
MXNET_INT64_TENSOR_SIZE build flag -> MXTPU_INT64=1).

MXTPU_INT64 is read at import (it flips jax_enable_x64), so each scenario
runs in a subprocess.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(code, int64=True, timeout=300):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("MXTPU_")}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and ".axon_site" not in p] + [REPO])
    if int64:
        env["MXTPU_INT64"] = "1"
    return subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=timeout, env=env)


pytestmark = pytest.mark.int64


def test_int64_values_beyond_int32_roundtrip_exact():
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "import numpy as np\n"
        "v = np.array([2**40 + 7, -(2**35), 2**31], np.int64)\n"
        "a = nd.array(v, dtype='int64')\n"
        "assert a.dtype == np.int64, a.dtype\n"
        "np.testing.assert_array_equal(a.asnumpy(), v)\n"
        "s = int((a + 1).sum().asnumpy())\n"
        "assert s == int(v.sum()) + 3, s\n"
        "b = nd.arange(2**33, 2**33 + 4, dtype='int64')\n"
        "np.testing.assert_array_equal(b.asnumpy(),\n"
        "    np.arange(2**33, 2**33 + 4, dtype=np.int64))\n")
    assert r.returncode == 0, r.stderr


def test_int64_ravel_unravel_beyond_int32():
    # flat index arithmetic over a shape whose product is 2^34 — the
    # canonical large-tensor indexing overflow (reference ravel.cc paths)
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "import numpy as np\n"
        "shape = (2**17, 2**17)      # product 2^34 > int32\n"
        "coords = nd.array(np.array([[2**16, 123], [2**16 + 1, 456]],\n"
        "                  np.int64).T, dtype='int64')\n"
        "flat = nd.ravel_multi_index(coords, shape=shape)\n"
        "want = np.ravel_multi_index(\n"
        "    np.array([[2**16, 123], [2**16 + 1, 456]], np.int64).T,\n"
        "    shape)\n"
        "np.testing.assert_array_equal(flat.asnumpy(), want)\n"
        "back = nd.unravel_index(flat, shape=shape)\n"
        "np.testing.assert_array_equal(\n"
        "    back.asnumpy(), np.array(np.unravel_index(want, shape)))\n")
    assert r.returncode == 0, r.stderr


def test_int64_reductions_and_cumsum_exact():
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "import numpy as np\n"
        "a = nd.full((8,), 2**30, dtype='int64')\n"
        "assert int(a.sum().asnumpy()) == 2**33\n"
        "c = nd.cumsum(a)\n"
        "assert int(c.asnumpy()[-1]) == 2**33\n"
        "assert c.asnumpy().dtype == np.int64\n"
        "p = nd.prod(nd.array([2**20, 2**20], dtype='int64'))\n"
        "assert int(p.asnumpy()) == 2**40\n")
    assert r.returncode == 0, r.stderr


def test_int64_shape_size_arrays():
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "import numpy as np\n"
        "x = nd.zeros((3, 5))\n"
        "assert nd.shape_array(x).asnumpy().dtype == np.int64\n"
        "assert nd.size_array(x).asnumpy().dtype == np.int64\n"
        "bins = nd.array([0.0, 1.0, 2.0])\n"
        "assert nd.digitize(nd.array([0.5]), bins).asnumpy().dtype \\\n"
        "    == np.int64\n"
        "assert nd.searchsorted(bins, nd.array([1.5])).asnumpy().dtype \\\n"
        "    == np.int64\n")
    assert r.returncode == 0, r.stderr


def test_without_flag_overflowing_values_warn():
    r = _run(
        "import warnings\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "import numpy as np\n"
        "with warnings.catch_warnings(record=True) as w:\n"
        "    warnings.simplefilter('always')\n"
        "    nd.array(np.array([2**40], np.int64))\n"
        "assert any('MXTPU_INT64' in str(x.message) for x in w), \\\n"
        "    [str(x.message) for x in w]\n",
        int64=False)
    assert r.returncode == 0, r.stderr


# ----------------------------------------------------------------------
# REAL huge allocations (reference tests/nightly/test_large_array.py
# allocates past 2^31 elements for real; VERDICT r4 missing #4). Opt-in:
# several GB of host RAM per test -> gated on MXTPU_TEST_HUGE=1.
# ----------------------------------------------------------------------

huge = pytest.mark.skipif(os.environ.get("MXTPU_TEST_HUGE", "") != "1",
                          reason="set MXTPU_TEST_HUGE=1 to run >2^31-"
                                 "element allocation tests (up to ~11GB "
                                 "RAM at peak)")


@huge
@pytest.mark.huge
def test_huge_vector_indexing_past_int32():
    """A real (2^31 + 64)-element vector: values planted beyond the
    int32 index range must be reachable by indexing, slicing, and
    argmax — the exact overflow class the reference's int64 build
    exists for."""
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "import numpy as np\n"
        "n = 2**31 + 64\n"
        "a = nd.zeros((n,), dtype='uint8')\n"
        "assert a.size == n and a.shape == (n,)\n"
        "a[2**31 + 7] = 9\n"
        "assert int(a[2**31 + 7].asnumpy()) == 9\n"
        "assert int(a[2**31 + 6].asnumpy()) == 0\n"
        "am = int(nd.argmax(a, axis=0).asnumpy())\n"
        "assert am == 2**31 + 7, am\n"
        "tail = a[2**31: 2**31 + 16].asnumpy()\n"
        "want = np.zeros(16, np.uint8); want[7] = 9\n"
        "np.testing.assert_array_equal(tail, want)\n",
        timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]


@huge
@pytest.mark.huge
def test_huge_2d_reduction_past_int32_elements():
    """(2^16, 2^15 + 2) = 2^31 + 2^17 elements: per-axis reduction and
    flat-size arithmetic stay exact past int32."""
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "import numpy as np\n"
        "rows, cols = 2**16, 2**15 + 2\n"
        "a = nd.full((rows, cols), 1, dtype='uint8')\n"
        "assert a.size == rows * cols > 2**31\n"
        # per-axis first (uint8 would wrap at 256; int32 holds a row sum
        # and costs 4 bytes/elem instead of materializing int64 at 8)
        "rs = nd.sum(a.astype('int32'), axis=1)\n"
        "assert rs.shape == (rows,)\n"
        "assert int(rs[0].asnumpy()) == cols\n"
        "total = int(nd.sum(rs.astype('int64')).asnumpy())\n"
        "assert total == rows * cols, total\n",
        timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
