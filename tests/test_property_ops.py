"""Property-based op-semantics tests (hypothesis): the edge-case-dense
surfaces SURVEY §7 "hard parts" calls out — MXNet reshape's 0/-1/-2/-3
special codes, broadcasting, slice/slice_axis conventions, take modes —
checked against an independent model (numpy re-implementations) across
generated shapes rather than a handful of fixed cases.  (The reference's
test_operator.py uses fixed cases only; property testing is additional
assurance, reference: src/operator/tensor/matrix_op-inl.h
InferReshapeShape, broadcast semantics in elemwise_binary_broadcast_op.h.)
"""
import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

import mxnet_tpu as mx
from mxnet_tpu import nd

# each example runs a couple of tiny jax ops; keep the per-case budget
# modest so the suite stays fast on the 1-core host
_SETTINGS = dict(max_examples=40, deadline=None)


def _shapes(min_dims=1, max_dims=4, max_side=5):
    return st.lists(st.integers(1, max_side), min_size=min_dims,
                    max_size=max_dims).map(tuple)


class TestReshapeCodes:
    @given(shape=_shapes(2, 4))
    @settings(**_SETTINGS)
    def test_zero_code_copies_input_dim(self, shape):
        """Code 0 at position i keeps the input's dim i."""
        a = nd.zeros(shape)
        out = nd.reshape(a, (0, -1))
        assert out.shape[0] == shape[0]
        assert int(np.prod(out.shape)) == int(np.prod(shape))

    @given(shape=_shapes(1, 4))
    @settings(**_SETTINGS)
    def test_minus1_infers_remainder(self, shape):
        a = nd.zeros(shape)
        out = nd.reshape(a, (-1,))
        assert out.shape == (int(np.prod(shape)),)

    @given(shape=_shapes(2, 4))
    @settings(**_SETTINGS)
    def test_minus2_copies_all_remaining(self, shape):
        """-2 copies ALL remaining input dims."""
        a = nd.zeros(shape)
        out = nd.reshape(a, (shape[0], -2))
        assert out.shape == shape

    @given(shape=_shapes(2, 4))
    @settings(**_SETTINGS)
    def test_minus3_merges_two_dims(self, shape):
        """-3 merges the next two input dims into one."""
        a = nd.zeros(shape)
        out = nd.reshape(a, (-3,) + shape[2:])
        assert out.shape == (shape[0] * shape[1],) + shape[2:]

    @given(shape=_shapes(1, 3), split=st.integers(1, 4))
    @settings(**_SETTINGS)
    def test_minus4_splits_dim(self, shape, split):
        """-4 a b splits an input dim into (a, b); -1 allowed as one
        factor."""
        d0 = shape[0] * split
        a = nd.zeros((d0,) + shape[1:])
        out = nd.reshape(a, (-4, split, -1) + shape[1:])
        assert out.shape == (split, shape[0]) + shape[1:]


class TestBroadcasting:
    @given(shape=_shapes(1, 3), data=st.data())
    @settings(**_SETTINGS)
    def test_broadcast_binary_matches_numpy(self, shape, data):
        """broadcast_add/mul/maximum follow numpy broadcasting for
        compatible shapes (1s inserted at random positions)."""
        other = tuple(data.draw(st.sampled_from([s, 1]))
                      for s in shape)
        rng = np.random.RandomState(0)
        x = rng.randn(*shape).astype(np.float32)
        y = rng.randn(*other).astype(np.float32)
        for op, ref in [(nd.broadcast_add, np.add),
                        (nd.broadcast_mul, np.multiply),
                        (nd.broadcast_maximum, np.maximum)]:
            np.testing.assert_allclose(
                op(nd.array(x), nd.array(y)).asnumpy(), ref(x, y),
                rtol=1e-6)

    @given(shape=_shapes(1, 3))
    @settings(**_SETTINGS)
    def test_broadcast_to_and_like(self, shape):
        target = tuple(s * 2 for s in shape)
        src = np.random.RandomState(1).randn(
            *[1] * len(shape)).astype(np.float32)
        out = nd.broadcast_to(nd.array(src), target)
        np.testing.assert_allclose(out.asnumpy(),
                                   np.broadcast_to(src, target))
        like = nd.zeros(target)
        out2 = nd.broadcast_like(nd.array(src), like)
        assert out2.shape == target


class TestSliceAndTake:
    @given(shape=_shapes(1, 3, max_side=6), data=st.data())
    @settings(**_SETTINGS)
    def test_slice_axis_matches_numpy(self, shape, data):
        axis = data.draw(st.integers(0, len(shape) - 1))
        begin = data.draw(st.integers(0, shape[axis] - 1))
        end = data.draw(st.integers(begin + 1, shape[axis]))
        x = np.random.RandomState(2).randn(*shape).astype(np.float32)
        out = nd.slice_axis(nd.array(x), axis=axis, begin=begin, end=end)
        ref = np.take(x, np.arange(begin, end), axis=axis)
        np.testing.assert_allclose(out.asnumpy(), ref)

    @given(n=st.integers(2, 8), data=st.data())
    @settings(**_SETTINGS)
    def test_take_clip_and_wrap_modes(self, n, data):
        idx = np.asarray(data.draw(st.lists(
            st.integers(-2 * n, 2 * n), min_size=1, max_size=6)))
        x = np.arange(float(n), dtype=np.float32)
        got_clip = nd.take(nd.array(x), nd.array(idx.astype(np.float32)),
                           mode="clip").asnumpy()
        np.testing.assert_allclose(got_clip,
                                   x[np.clip(idx, 0, n - 1)])
        got_wrap = nd.take(nd.array(x), nd.array(idx.astype(np.float32)),
                           mode="wrap").asnumpy()
        np.testing.assert_allclose(got_wrap, x[idx % n])

    @given(shape=_shapes(2, 2, max_side=6))
    @settings(**_SETTINGS)
    def test_pick_matches_manual_gather(self, shape):
        rng = np.random.RandomState(3)
        x = rng.randn(*shape).astype(np.float32)
        idx = rng.randint(0, shape[1], shape[0]).astype(np.float32)
        got = nd.pick(nd.array(x), nd.array(idx), axis=1).asnumpy()
        ref = x[np.arange(shape[0]), idx.astype(int)]
        np.testing.assert_allclose(got, ref)


class TestGradProperties:
    @given(shape=_shapes(1, 3, max_side=4))
    @settings(max_examples=15, deadline=None)
    def test_sum_grad_is_ones(self, shape):
        from mxnet_tpu import autograd
        a = nd.array(np.random.RandomState(4).randn(*shape)
                     .astype(np.float32))
        a.attach_grad()
        with autograd.record():
            y = a.sum()
        y.backward()
        np.testing.assert_allclose(a.grad.asnumpy(), np.ones(shape))

    @given(shape=_shapes(1, 2, max_side=4))
    @settings(max_examples=15, deadline=None)
    def test_mul_grad_product_rule(self, shape):
        from mxnet_tpu import autograd
        rng = np.random.RandomState(5)
        xv, yv = (rng.randn(*shape).astype(np.float32) for _ in range(2))
        x, y = nd.array(xv), nd.array(yv)
        x.attach_grad()
        y.attach_grad()
        with autograd.record():
            z = (x * y).sum()
        z.backward()
        np.testing.assert_allclose(x.grad.asnumpy(), yv, rtol=1e-6)
        np.testing.assert_allclose(y.grad.asnumpy(), xv, rtol=1e-6)
