"""AMP mixed precision (reference: tests/python/unittest/test_amp.py).

Checks: op-list casting (MXU ops run bf16, blacklist ops run fp32),
end-to-end bf16 training step, fp16 dynamic loss scaling skip-on-overflow.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon

nd = mx.nd


@pytest.fixture
def amp_bf16():
    amp.init(target_dtype="bfloat16")
    yield
    amp._deinit_for_tests()


@pytest.fixture
def amp_fp16():
    amp.init(target_dtype="float16")
    yield
    amp._deinit_for_tests()


def test_target_ops_cast_down(amp_bf16):
    a = nd.random.uniform(shape=(4, 8))
    b = nd.random.uniform(shape=(8, 4))
    out = nd.dot(a, b)
    assert str(out.data.dtype) == "bfloat16"


def test_fp32_ops_cast_up(amp_bf16):
    x = nd.random.uniform(shape=(4, 8)).astype("bfloat16")
    out = nd.softmax(x)
    assert str(out.data.dtype) == "float32"


def test_bf16_training_step(amp_bf16):
    net = gluon.nn.Dense(4)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    x = nd.random.uniform(shape=(8, 16))
    y = nd.zeros((8,))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        out = net(x)
        loss = loss_fn(out, y)
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
    w_before = net.weight.data().asnumpy().copy()
    trainer.step(8)
    assert not np.allclose(net.weight.data().asnumpy(), w_before)


def test_fp16_loss_scaler_overflow_skips_step(amp_fp16):
    net = gluon.nn.Dense(2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    scaler = trainer._amp_loss_scaler
    assert scaler.loss_scale > 1.0
    x = nd.random.uniform(shape=(4, 4))
    with autograd.record():
        out = net(x)
        loss = (out * float("inf")).sum()
        loss.backward()
    w_before = net.weight.data().asnumpy().copy()
    s_before = scaler.loss_scale
    trainer.step(4)
    # overflow: weights unchanged, scale halved
    assert np.allclose(net.weight.data().asnumpy(), w_before)
    assert scaler.loss_scale == s_before / 2


def test_loss_scaler_growth():
    s = amp.LossScaler(init_scale=4.0, scale_window=2)
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 8.0
    s.update_scale(True)
    assert s.loss_scale == 4.0


def test_convert_hybrid_block(amp_bf16):
    net = gluon.nn.Dense(3)
    net.initialize()
    net(nd.zeros((2, 5)))
    amp.convert_hybrid_block(net)
    assert str(net.weight.data().data.dtype) == "bfloat16"


def test_amp_lists_and_convert_model():
    """amp.list_lp16_ops/list_fp32_ops + convert_model (reference
    contrib/amp Module-API surface)."""
    from mxnet_tpu import amp
    lp16, fp32 = amp.list_lp16_ops(), amp.list_fp32_ops()
    assert "dot" in lp16 or "FullyConnected" in lp16
    assert len(fp32) > 0 and not set(lp16) & set(fp32)
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    args = {"fc_weight": nd.ones((3, 5)), "fc_bias": nd.zeros((3,))}
    try:
        s2, a2, x2 = amp.convert_model(out, args, {},
                                       cast_optional_params=True)
        assert s2 is out
        assert str(a2["fc_weight"].dtype) == "bfloat16"
    finally:
        amp._deinit_for_tests()


def test_convert_model_guards():
    """Review findings: integer aux params keep their dtype; a second
    convert_model with a DIFFERENT target dtype raises instead of
    silently keeping the old policy; reference kwargs are accepted."""
    from mxnet_tpu import amp
    import numpy as np
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    args = {"fc_weight": nd.ones((2, 3))}
    aux = {"step": nd.array([4], dtype="int32")}
    try:
        aux["bn_running_mean"] = nd.array([0.1, 0.2])
        _, a2, x2 = amp.convert_model(out, args, aux,
                                      excluded_sym_names=["fc"],
                                      cast_optional_params=True)
        assert str(a2["fc_weight"].dtype) == "bfloat16"
        assert x2["step"].dtype == np.int32          # int aux untouched
        assert x2["bn_running_mean"].dtype == np.float32  # norm stays fp32
        with pytest.raises(mx.MXNetError, match="already initialized"):
            amp.convert_model(out, args, aux, target_dtype="float16")
        with pytest.raises(mx.MXNetError, match="FIRST"):
            amp.convert_model(out, args, aux, fp32_ops=["exp"])
        # aux_params=None normalizes to {} on every path
        _, _, x3 = amp.convert_model(out, args, None)
        assert x3 == {}
    finally:
        amp._deinit_for_tests()
