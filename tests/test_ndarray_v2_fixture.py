"""Hand-encoded NDARRAY_V2 fixture: breaks reader/writer circularity.

The golden-fixture test in test_model_store.py generates its .params blob
with this repo's own `save_legacy`, so a shared layout bug in reader and
writer would cancel out and still round-trip.  Here the container bytes
are spelled out as comment-mapped hex literals straight from the
documented dmlc layout (reference src/ndarray/ndarray.cc Save/Load:
uint64 file magic 0x112, uint64 reserved, uint64 count, per record
[uint32 magic NDARRAY_V2=0xF993FAC9 (+int32 stype) or V3=0xF993FAC8,
uint32 ndim, int64 dims, uint32 ctx dev_type, uint32 ctx dev_id, uint32
dtype flag, raw payload], then uint64 name-count + length-prefixed
names).  If the reader decodes names, shapes, dtypes, and exact values
from THESE bytes, a reader bug can no longer be masked by the writer.
"""
import numpy as np

import mxnet_tpu as mx

nd = mx.nd


def _fixture_record_f32() -> bytes:
    """One dense float32 (2,3) record, NDARRAY_V2 magic (with stype)."""
    return bytes.fromhex(
        "c9fa93f9"          # uint32 record magic 0xF993FAC9 (NDARRAY_V2)
        "00000000"          # int32 stype 0 (kDefaultStorage = dense)
        "02000000"          # uint32 ndim = 2
        "0200000000000000"  # int64 dim0 = 2
        "0300000000000000"  # int64 dim1 = 3
        "01000000"          # uint32 ctx dev_type = 1 (cpu)
        "00000000"          # uint32 ctx dev_id = 0
        "00000000"          # uint32 dtype flag 0 = float32
        # row-major payload, little-endian IEEE754 single:
        "0000c03f"          # 1.5    (0x3FC00000)
        "000000c0"          # -2.0   (0xC0000000)
        "0000803e"          # 0.25   (0x3E800000)
        "00004040"          # 3.0    (0x40400000)
        "000000bf"          # -0.5   (0xBF000000)
        "0000c842"          # 100.0  (0x42C80000)
    )


def _fixture_blob() -> bytes:
    header = bytes.fromhex(
        "1201000000000000"  # uint64 file magic 0x112
        "0000000000000000"  # uint64 reserved
        "0300000000000000"  # uint64 ndarray count = 3
    )
    rec2 = bytes.fromhex(   # int64 (3,) record, V3 magic (NO stype field)
        "c8fa93f9"          # uint32 record magic 0xF993FAC8 (pre-stype)
        "01000000"          # uint32 ndim = 1
        "0300000000000000"  # int64 dim0 = 3
        "01000000"          # uint32 ctx dev_type = 1 (cpu)
        "00000000"          # uint32 ctx dev_id = 0
        "06000000"          # uint32 dtype flag 6 = int64
        "ffffffffffffffff"  # -1
        "0500004000000000"  # 2**30 + 5  (0x40000005)
        "0700000000000000"  # 7
    )
    rec3 = bytes.fromhex(   # float16 (1,2) record, NDARRAY_V2 magic
        "c9fa93f9"          # record magic
        "00000000"          # stype dense
        "02000000"          # ndim = 2
        "0100000000000000"  # dim0 = 1
        "0200000000000000"  # dim1 = 2
        "01000000"          # ctx dev_type
        "00000000"          # ctx dev_id
        "02000000"          # dtype flag 2 = float16
        "003c"              # 1.0   (0x3C00)
        "00c1"              # -2.5  (0xC100)
    )
    names = bytes.fromhex(
        "0300000000000000"          # uint64 name count = 3
        "0c00000000000000"          # uint64 len("conv0_weight") = 12
        "636f6e76305f776569676874"  # "conv0_weight"
        "0800000000000000"          # uint64 len("fc0_bias") = 8
        "6663305f62696173"          # "fc0_bias"
        "0500000000000000"          # uint64 len("gamma") = 5
        "67616d6d61"                # "gamma"
    )
    return header + _fixture_record_f32() + rec2 + rec3 + names


def test_reader_decodes_hand_encoded_bytes(tmp_path):
    path = tmp_path / "hand_encoded.params"
    path.write_bytes(_fixture_blob())
    out = nd.load(str(path))
    assert sorted(out) == ["conv0_weight", "fc0_bias", "gamma"]

    w = out["conv0_weight"]
    assert w.shape == (2, 3) and str(w.dtype) == "float32"
    np.testing.assert_array_equal(
        w.asnumpy(), np.array([[1.5, -2.0, 0.25], [3.0, -0.5, 100.0]],
                              np.float32))

    b = out["fc0_bias"]
    assert b.shape == (3,)
    # the reader decodes int64; NDArray then narrows to int32 unless the
    # x64 switch is on (MXTPU_INT64 policy, exercised in test_int64_large)
    import jax
    want = "int64" if jax.config.jax_enable_x64 else "int32"
    assert str(b.dtype) == want
    np.testing.assert_array_equal(
        b.asnumpy(), np.array([-1, 2 ** 30 + 5, 7], want))

    g = out["gamma"]
    assert g.shape == (1, 2) and str(g.dtype) == "float16"
    np.testing.assert_array_equal(
        g.asnumpy(), np.array([[1.0, -2.5]], np.float16))


def test_load_frombuffer_matches_load(tmp_path):
    from mxnet_tpu.ndarray.utils import load_frombuffer
    out = load_frombuffer(_fixture_blob())
    assert out["conv0_weight"].asnumpy()[1, 2] == 100.0


def test_writer_reproduces_hand_encoded_record_bytes(tmp_path):
    """save_legacy must emit byte-identical output for the same float32
    record — pinning the WRITER to the documented layout too (a writer
    drift would otherwise only surface when reference-era MXNet tried to
    read our exports)."""
    from mxnet_tpu.ndarray.utils import save_legacy
    path = tmp_path / "writer.params"
    save_legacy(str(path),
                {"conv0_weight":
                 nd.array(np.array([[1.5, -2.0, 0.25], [3.0, -0.5, 100.0]],
                                   np.float32))})
    blob = path.read_bytes()
    expected = (
        bytes.fromhex("1201000000000000"    # file magic
                      "0000000000000000"    # reserved
                      "0100000000000000")   # count = 1
        + _fixture_record_f32()
        + bytes.fromhex("0100000000000000"            # name count = 1
                        "0c00000000000000"            # len = 12
                        "636f6e76305f776569676874")   # "conv0_weight"
    )
    assert blob == expected
