"""Losses vs manual formulas + metric semantics
(reference: tests/python/unittest/test_loss.py, test_metric.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, metric

nd = mx.nd
loss_mod = gluon.loss


def test_l2_l1_loss():
    pred = nd.array([[1.0, 2.0], [3.0, 4.0]])
    label = nd.array([[0.0, 2.0], [3.0, 2.0]])
    l2 = loss_mod.L2Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(l2, [0.25, 1.0])      # mean of sq diff / 2
    l1 = loss_mod.L1Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(l1, [0.5, 1.0])


def test_softmax_ce_sparse_vs_dense():
    pred = nd.array([[2.0, 1.0, 0.0], [0.0, 1.0, 2.0]])
    sparse = loss_mod.SoftmaxCrossEntropyLoss()(
        pred, nd.array([0, 2])).asnumpy()
    dense = loss_mod.SoftmaxCrossEntropyLoss(sparse_label=False)(
        pred, nd.array([[1.0, 0, 0], [0, 0, 1.0]])).asnumpy()
    np.testing.assert_allclose(sparse, dense, rtol=1e-5)
    logp = np.log(np.exp([2.0, 1.0, 0.0]) / np.exp([2.0, 1.0, 0.0]).sum())
    np.testing.assert_allclose(sparse[0], -logp[0], rtol=1e-5)


def test_sigmoid_bce():
    pred = nd.array([[0.5, -0.5]])
    label = nd.array([[1.0, 0.0]])
    out = loss_mod.SigmoidBinaryCrossEntropyLoss()(pred, label).asnumpy()
    p = 1 / (1 + np.exp(-np.array([0.5, -0.5])))
    ref = -(np.log(p[0]) + np.log(1 - p[1])) / 2
    np.testing.assert_allclose(out, [ref], rtol=1e-5)


def test_kl_div_loss():
    pred = nd.log(nd.array([[0.25, 0.75]]))
    label = nd.array([[0.5, 0.5]])
    out = loss_mod.KLDivLoss(from_logits=True)(pred, label).asnumpy()
    ref = (0.5 * np.log(0.5 / 0.25) + 0.5 * np.log(0.5 / 0.75)) / 2
    np.testing.assert_allclose(out, [ref], rtol=1e-4)


def test_huber_loss_regions():
    pred = nd.array([[0.5, 3.0]])
    label = nd.array([[0.0, 0.0]])
    out = loss_mod.HuberLoss(rho=1.0)(pred, label).asnumpy()
    ref = (0.5 * 0.25 + (3.0 - 0.5)) / 2
    np.testing.assert_allclose(out, [ref], rtol=1e-5)


def test_triplet_loss_margin():
    a = nd.array([[0.0, 0.0]])
    p = nd.array([[0.1, 0.0]])
    n = nd.array([[3.0, 0.0]])
    out = loss_mod.TripletLoss(margin=1.0)(a, p, n).asnumpy()
    assert out[0] == 0.0                     # separation >> margin
    out2 = loss_mod.TripletLoss(margin=1.0)(a, n, p).asnumpy()
    assert out2[0] > 0


def test_ctc_loss_runs():
    pred = nd.random.uniform(shape=(4, 2, 5))      # (T, B, C)
    label = nd.array([[1, 2], [2, 3]])
    out = loss_mod.CTCLoss(layout="TNC")(pred, label)
    assert out.shape == (2,)
    assert np.isfinite(out.asnumpy()).all()


# -- metrics ---------------------------------------------------------------

def test_accuracy_metric():
    m = metric.Accuracy()
    m.update(nd.array([0, 1, 1]), nd.array([[0.9, 0.1], [0.3, 0.7],
                                            [0.8, 0.2]]))
    name, acc = m.get()
    assert name == "accuracy"
    assert acc == pytest.approx(2.0 / 3.0)
    m.reset()
    assert np.isnan(m.get()[1])


def test_topk_accuracy():
    m = metric.TopKAccuracy(top_k=2)
    preds = nd.array([[0.1, 0.2, 0.7], [0.6, 0.3, 0.1]])
    m.update(nd.array([1, 2]), preds)
    assert m.get()[1] == pytest.approx(0.5)


def test_f1_metric():
    m = metric.F1()
    m.update(nd.array([1, 0, 1, 0]),
             nd.array([[0.1, 0.9], [0.9, 0.1], [0.2, 0.8], [0.3, 0.7]]))
    # preds: 1, 0, 1, 1 -> tp=2 fp=1 fn=0 -> P=2/3 R=1 F1=0.8
    assert m.get()[1] == pytest.approx(0.8)


def test_perplexity():
    m = metric.Perplexity(ignore_label=None)
    m.update(nd.array([0]), nd.array([[0.5, 0.5]]))
    assert m.get()[1] == pytest.approx(2.0)


def test_mae_mse_rmse():
    label = nd.array([[1.0, 2.0]])
    pred = nd.array([[2.0, 4.0]])
    assert metric.MAE().get_name_value() is not None
    m = metric.MAE()
    m.update(label, pred)
    assert m.get()[1] == pytest.approx(1.5)
    m = metric.MSE()
    m.update(label, pred)
    assert m.get()[1] == pytest.approx(2.5)
    m = metric.RMSE()
    m.update(label, pred)
    assert m.get()[1] == pytest.approx(np.sqrt(2.5))


def test_composite_and_custom():
    comp = metric.CompositeEvalMetric()
    comp.add(metric.Accuracy())
    comp.add(metric.MAE())
    comp.update(nd.array([1]), nd.array([[0.2, 0.8]]))
    names, vals = comp.get()
    assert len(names) == 2
    cm = metric.CustomMetric(lambda l, p: 0.5, name="half")
    cm.update(nd.array([1]), nd.array([1.0]))
    assert cm.get()[1] == 0.5


def test_metric_create_by_name():
    m = metric.create("acc")
    assert isinstance(m, metric.Accuracy)
