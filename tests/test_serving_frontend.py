"""Serving front-end (ISSUE 12): CoW prefix cache, chunked/batched
prefill, multi-replica router.

THE acceptance gates:

- a system prompt shared by >= 3 requests is prefilled exactly ONCE
  (dispatch- and token-counted) and every request's decode logits are
  BITWISE (fp32) the cold-path engine's;
- eviction under block pressure never frees a block a live sequence
  still references (refcount > 0);
- chunked prefill does the same work in strictly fewer dispatches than
  one-prompt-per-boundary, with zero compiles after warmup;
- a replica kill mid-traffic requeues with zero lost/duplicated
  requests and solo-reference outputs (the chaos scenario, also wired
  as ``tools/tpu_queue_runner.py --chaos serving``).

Every engine in this module shares ONE compile cache (the Router's
fleet discipline), so the file pays the graph compiles once.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import NotSupportedError
from mxnet_tpu.gluon.model_zoo.nlp.llama import (LlamaConfig,
                                                 LlamaForCausalLM)
from mxnet_tpu.serving import (ContinuousBatcher, DoubleFreeError,
                               InferenceEngine, PagedKVCache, PrefixCache,
                               Request, Router)

nd = mx.nd

_CC = {}      # module-wide shared compile cache (one compile per graph)


@pytest.fixture(scope="module")
def net():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_seq_len=64, tie_embeddings=True)
    n = LlamaForCausalLM(cfg)
    n.initialize()
    n(nd.array([[1, 2, 3]], dtype="int32"))
    n.hybridize()
    return n


def _engine(net, prefix=False, chunk=8, **kw):
    kw.setdefault("max_batch", 3)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_context", 32)
    eng = InferenceEngine(net, prefill_chunk=chunk, prefix_cache=prefix,
                          compile_cache=_CC, **kw)
    return eng.warmup()


def _solo_stream(eng, prompt, n_decode):
    """Cold path: full-prompt prefill + greedy decode, capturing the
    decode logits rows."""
    tok, _ = eng.prefill("__solo__", prompt)
    cur = list(prompt) + [int(tok)]
    rows = []
    for _ in range(n_decode):
        pos = len(cur) - 1
        assert eng.reserve("__solo__", pos)
        nxt, lg = eng.decode([("__solo__", cur[-1], pos)])
        rows.append(lg[0].copy())
        cur.append(int(nxt[0]))
    eng.release("__solo__")
    return cur[len(prompt):], rows


# ----------------------------------------------------------------------
# kv-cache refcounts: CoW plumbing + typed errors
# ----------------------------------------------------------------------

def test_refcounts_fork_cow_and_typed_double_free():
    c = PagedKVCache(num_layers=1, num_kv_heads=2, head_dim=8,
                     num_blocks=8, block_size=4, max_batch=2)
    assert c.alloc("a", 8)                       # blocks x2, ref 1 each
    ta = c.table("a")
    c.adopt("b", ta, 8)                          # full share
    assert all(c.refcount(b) == 2 for b in ta)
    # CoW: writing into b's first block must fork it
    copies = c.prepare_write("b", 0, 4)
    assert len(copies) == 1
    old, new = copies[0]
    assert old == ta[0] and new not in ta
    assert c.refcount(old) == 1 and c.refcount(new) == 1
    assert c.cow_copies == 1
    # unshared range: no copies (a's first block is solely a's now)
    assert c.prepare_write("a", 0, 4) == []
    # free only decrements: a's blocks survive b's remaining share
    c.free("a")
    assert c.refcount(ta[1]) == 1               # b still holds it
    assert ta[1] not in c._free
    c.free("b")
    assert c.blocks_in_use == 0
    assert c.check_leaks()
    # typed double free / underflow
    with pytest.raises(DoubleFreeError):
        c.free("a")
    assert c.alloc("d", 4)
    blk = c.table("d")[0]
    c.unref(blk)
    with pytest.raises(DoubleFreeError):
        c.unref(blk)
    with pytest.raises(DoubleFreeError):
        c.ref(blk)                              # unallocated again
    del c._tables["d"], c._lens["d"]            # drop the dangling table


def test_prepare_write_pool_exhausted_rolls_back():
    c = PagedKVCache(num_layers=1, num_kv_heads=2, head_dim=8,
                     num_blocks=4, block_size=4, max_batch=2)
    assert c.alloc("a", 12)                      # all 3 blocks
    c.adopt("b", c.table("a"), 12)
    assert c.prepare_write("b", 0, 4) is None    # no free block to fork
    assert c.alloc_failures == 1
    assert c.cow_copies == 0
    assert c.table("b") == c.table("a")          # plan fully undone
    c.free("a")
    c.free("b")
    assert c.check_leaks()


def test_prefix_cache_chain_lookup_partial_and_lru_eviction():
    c = PagedKVCache(num_layers=1, num_kv_heads=2, head_dim=8,
                     num_blocks=8, block_size=4, max_batch=2)
    pc = PrefixCache(c)
    toks = list(range(10))                       # 2 full blocks + 2 tail
    assert c.alloc("seed", 10)
    pc.insert("seed", toks)                      # nodes: 4,4-full + 2-tail
    assert pc.held_blocks() == 3
    c.free("seed")                               # chains keep the blocks
    assert c.blocks_in_use == 3
    # full-chain hit capped at len-1: an identical prompt reuses the two
    # full blocks and the partial tail
    n, blocks = pc.lookup(toks + [99])
    assert n == 10 and len(blocks) == 3
    # diverging second block: only the first matches
    n, _ = pc.lookup([0, 1, 2, 3, 9, 9, 9, 9, 5])
    assert n == 4
    # miss
    n, _ = pc.lookup([7, 7, 7, 7, 7])
    assert n == 0
    # attach bumps refcounts; eviction must NOT free the shared blocks
    assert pc.attach("req", toks + [42]) == 10
    shared = c.table("req")
    free_before = c.num_free_blocks
    pc.evict(blocks_needed=c.num_blocks)         # drop every chain
    assert pc.held_blocks() == 0
    # chains dropped their refs, but req still holds all three blocks:
    # none may have been recycled
    assert all(c.refcount(b) == 1 for b in shared)
    assert c.num_free_blocks == free_before      # nothing reclaimed
    c.free("req")
    assert c.check_leaks()


# ----------------------------------------------------------------------
# THE gate: shared system prompt prefilled once, decode BITWISE cold
# ----------------------------------------------------------------------

def test_shared_prefix_prefilled_once_and_decode_bitwise(net):
    rng = np.random.RandomState(3)
    sys_prompt = rng.randint(0, 64, (12,)).tolist()
    users = [rng.randint(0, 64, (n,)).tolist() for n in (5, 7, 3)]
    cold = _engine(net, prefix=False)
    refs = [_solo_stream(cold, sys_prompt + u, 4) for u in users]

    eng = _engine(net, prefix=True, num_blocks=25)
    assert eng.pin_prefix(sys_prompt)
    pinned = eng.stats["prompt_tokens_computed"]
    assert pinned == len(sys_prompt)             # computed exactly once
    # serve the three requests; capture each decode's logits rows
    for u, (ref_toks, ref_rows) in zip(users, refs):
        b = ContinuousBatcher(eng)
        rows = []
        orig = eng.decode

        def capture(entries, _orig=orig, _rows=rows):
            nxt, lg = _orig(entries)
            _rows.append(lg[0].copy())
            return nxt, lg

        eng.decode = capture
        req = b.submit(Request(sys_prompt + u, max_new_tokens=5))
        b.run()
        eng.decode = orig
        assert req.generated[:4] == ref_toks[:4]
        for got, ref in zip(rows, ref_rows):
            np.testing.assert_array_equal(
                got, ref, err_msg="prefix-path decode is not bitwise "
                                  "the cold path")
    # the system prompt was never recomputed: only the user suffixes
    assert eng.stats["prompt_tokens_computed"] == \
        pinned + sum(len(u) for u in users)
    assert eng.prefix_cache.hits == 3
    assert eng.prefix_cache.hit_rate() == 1.0
    # decode past the partial tail block forked it per request
    assert eng.cache.cow_copies >= 3
    assert eng.stats["compiles_after_warmup"] == 0
    # leak sweep: all sequences released, only chains hold blocks
    assert eng.cache.check_leaks(
        holders=eng.prefix_cache.held_blocks())


def test_eviction_under_pressure_completes_and_leaks_clean(net):
    """Pool pressure forces LRU chain eviction mid-traffic; live
    requests keep their (refcount > 1) blocks and finish with the cold
    streams; the pool balances afterwards."""
    rng = np.random.RandomState(9)
    sys_prompt = rng.randint(0, 64, (12,)).tolist()
    cold = _engine(net, prefix=False)
    eng = _engine(net, prefix=True, num_blocks=13)   # 12 allocatable
    assert eng.pin_prefix(sys_prompt)
    # unrelated chains to be LRU victims
    for seed in (21, 22):
        eng.pin_prefix(rng.randint(0, 64, (8,)).tolist())
    b = ContinuousBatcher(eng)
    reqs, refs = [], []
    for n in (6, 9, 4, 7):
        prompt = sys_prompt + rng.randint(0, 64, (n,)).tolist()
        refs.append(_solo_stream(cold, prompt, 3)[0])
        reqs.append(b.submit(Request(prompt, max_new_tokens=4)))
    b.run()
    assert all(r.done for r in reqs)
    for r, ref in zip(reqs, refs):
        assert r.generated == ref               # solo-exact streams
    assert eng.prefix_cache.evictions > 0        # pressure actually hit
    assert eng.stats["compiles_after_warmup"] == 0
    assert eng.cache.check_leaks(
        holders=eng.prefix_cache.held_blocks())


# ----------------------------------------------------------------------
# chunked prefill: fewer dispatches for identical work
# ----------------------------------------------------------------------

def test_chunked_prefill_fewer_dispatches_same_work(net):
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 64, (3 + i % 5,)).tolist()
               for i in range(6)]

    def serve(eng):
        b = ContinuousBatcher(eng)
        reqs = [b.submit(Request(p, max_new_tokens=3)) for p in prompts]
        b.run()
        return [tuple(r.generated) for r in reqs], b

    serial = _engine(net, prefix=False, chunk=0)
    out_serial, _ = serve(serial)
    chunked = _engine(net, prefix=False, chunk=8)
    out_chunked, bc = serve(chunked)
    assert out_serial == out_chunked                 # identical work
    serial_dispatches = serial.stats["prefill_calls"]
    chunk_dispatches = (chunked.stats["chunk_prefill_calls"]
                        + chunked.stats["prefill_calls"])
    assert serial_dispatches == len(prompts)         # one per boundary
    assert chunk_dispatches < serial_dispatches      # the amortization
    assert serial.stats["compiles_after_warmup"] == 0
    assert chunked.stats["compiles_after_warmup"] == 0
    assert chunked.cache.check_leaks()
    # a long prompt still admits through bounded tail chunks
    long = _engine(net, prefix=False, chunk=8)
    b = ContinuousBatcher(long)
    req = b.submit(Request(rng.randint(0, 64, (20,)).tolist(),
                           max_new_tokens=2))
    b.run()
    assert req.done and len(req.generated) == 2
    assert long.stats["chunk_prefill_calls"] == 3    # ceil(20 / 8)
    assert long.stats["compiles_after_warmup"] == 0


# ----------------------------------------------------------------------
# router: shared warmup, least-loaded admission, death -> requeue
# ----------------------------------------------------------------------

def _router(net, replicas=2, **ekw):
    def factory(_cc):
        # the module-wide cache stands in for the router's: the fleet
        # still pays each graph once (replica engines compile nothing)
        return InferenceEngine(net, max_batch=3, block_size=8,
                               max_context=32, prefill_chunk=8,
                               prefix_cache=True, compile_cache=_CC,
                               **ekw)
    return Router(factory, replicas=replicas)


def test_router_shared_warmup_and_least_loaded_admission(net):
    router = _router(net, replicas=2)
    # the whole fleet compiled nothing new (module cache already warm),
    # and replica 1's warmup skipped every graph replica 0 would build
    for rep in router.replicas:
        assert rep.engine.stats["compiles"] == 0
    m = router.manifest()
    assert m["epoch"] == 0 and len(m["replicas"]) == 2
    assert all(r["mesh"] == "dp1" for r in m["replicas"])
    assert all(r["prefix_cache"] for r in m["replicas"])
    # admission spreads load: queue one replica, the next request must
    # land on the other
    rng = np.random.RandomState(7)
    p = rng.randint(0, 64, (5,)).tolist()
    r1 = router.submit(Request(p, max_new_tokens=2))
    rid1 = router._assigned[r1.id]
    r2 = router.submit(Request(p, max_new_tokens=2))
    assert router._assigned[r2.id] != rid1
    router.drive()
    assert len(router.finished()) == 2
    assert r1.generated == r2.generated              # same prompt


def test_router_death_requeues_zero_lost_or_dup(net):
    from mxnet_tpu.testing import faults
    rng = np.random.RandomState(11)
    sys_prompt = rng.randint(0, 64, (12,)).tolist()
    prompts = [sys_prompt + rng.randint(0, 64, (3 + i,)).tolist()
               for i in range(5)]
    cold = _engine(net, prefix=False)
    refs = [_solo_stream(cold, p, 3)[0] for p in prompts]
    router = _router(net, replicas=2)
    for rep in router.replicas:
        assert rep.engine.pin_prefix(sys_prompt)
    reqs = [router.submit(Request(p, max_new_tokens=4))
            for p in prompts]
    with faults.inject("serving.replica1.step", at=2):
        router.drive()
    fin = router.finished()
    assert sorted(r.id for r in fin) == sorted(r.id for r in reqs)
    assert router.epoch == 1 and router.requeues >= 1
    for r, ref in zip(reqs, refs):
        assert r.generated == ref                   # greedy, solo-exact
    st = router.stats()
    assert st["compiles_after_warmup"] == 0
    assert st["live"] == 1
    # survivor balances: every block back except the prefix chains
    survivor = router.live_replicas()[0]
    assert survivor.engine.cache.check_leaks(
        holders=survivor.engine.prefix_cache.held_blocks())


def test_router_drain_replica_requeues_and_add_replica_grows(net):
    """ISSUE 13: a graceful drain (preemption notice / autoscale-away)
    evacuates the doomed replica with zero lost or duplicated requests,
    and add_replica grows the fleet from the SHARED warmup compile
    cache — the newcomer compiles nothing."""
    rng = np.random.RandomState(17)
    prompts = [rng.randint(0, 64, (4 + i,)).tolist() for i in range(4)]
    router = _router(net, replicas=2)
    reqs = [router.submit(Request(p, max_new_tokens=3))
            for p in prompts]
    moved = router.drain_replica(1, reason="notice:test")
    assert moved >= 1                        # its inbox was evacuated
    assert router.epoch == 1
    assert [e["kind"] for e in router.events] == ["replica_drained"]
    assert not router.replicas[1].alive
    rep = router.add_replica()
    assert rep.rid == 2 and rep.alive and router.epoch == 2
    router.drive()
    fin = router.finished()
    assert sorted(r.id for r in fin) == sorted(r.id for r in reqs)
    assert len(fin) == len(reqs)             # zero lost, zero dup
    assert router.stats()["compiles_after_warmup"] == 0
    # the last live replica refuses to drain (typed, not a wedge)
    router.drain_replica(2, reason="autoscale")
    with pytest.raises(mx.base.MXNetError, match="last live replica"):
        router.drain_replica(0)


def test_router_shedding_rejects_new_admissions_only(net):
    """Degradation-ladder rung 1: shedding rejects NEW submits with the
    typed AdmissionShed; requeues (a drain) are exempt, so in-flight
    work still completes exactly once."""
    from mxnet_tpu.serving import AdmissionShed
    rng = np.random.RandomState(19)
    router = _router(net, replicas=2)
    reqs = [router.submit(Request(rng.randint(0, 64, (5,)).tolist(),
                                  max_new_tokens=2)) for _ in range(2)]
    assert router.set_shedding(True, reason="test") is True
    with pytest.raises(AdmissionShed):
        router.submit(Request([1, 2, 3], max_new_tokens=1))
    router.drain_replica(1, reason="notice:test")   # requeues pass
    router.drive()
    assert all(r.done for r in reqs)
    router.set_shedding(False)
    r3 = router.submit(Request([1, 2, 3], max_new_tokens=1))
    router.drive()
    assert r3.done


def test_router_notice_board_drains_doomed_replica(net):
    """A NoticeBoard wired into the router drains the noticed replica
    at the next drive boundary; a revoked notice cancels the drain."""
    from mxnet_tpu import elastic
    from mxnet_tpu.testing import faults
    clock = faults.FakeClock(100.0)
    board = elastic.NoticeBoard(now=clock)
    router = _router(net, replicas=2)
    router.attach_notices(board)
    rng = np.random.RandomState(23)
    # revoked before any boundary: no drain
    board.post(0, grace_s=60, kind="maintenance")
    board.revoke(0)
    reqs = [router.submit(Request(rng.randint(0, 64, (4,)).tolist(),
                                  max_new_tokens=2)) for _ in range(2)]
    board.post(1, grace_s=60, kind="preempt")
    router.drive()
    assert router.replicas[0].alive          # revocation cancelled it
    assert not router.replicas[1].alive      # the noticed one drained
    assert all(r.done for r in reqs)
    assert board.stats()["pending"] == 0


def test_router_threaded_mode_racecheck_clean(net):
    from mxnet_tpu.lint import racecheck
    racecheck.reset()
    racecheck.configure(enabled=True)
    try:
        router = _router(net, replicas=2)
        router.start()
        rng = np.random.RandomState(13)
        reqs = [router.submit(
            Request(rng.randint(0, 64, (4 + i,)).tolist(),
                    max_new_tokens=2)) for i in range(4)]
        router.wait_all_done(timeout=120)
        router.stop()
        assert all(r.done for r in reqs)
        assert len(router.finished()) == 4
        assert racecheck.findings() == []
    finally:
        racecheck.configure(enabled=False)
        racecheck.reset()


@pytest.mark.slow
def test_serving_chaos_scenario(tmp_path):
    """The tier-1 wiring of ``--chaos serving`` (like the elastic
    scenarios): replica kill mid-traffic, requeue, solo-exact outputs,
    flight dump, racecheck, KV leak sweep — one verdict dict."""
    from mxnet_tpu.testing.chaos import run_serving_scenario
    r = run_serving_scenario(workdir=str(tmp_path))
    assert r["ok"], r
    assert r["no_lost_or_dup"] and r["outputs_match_solo"]
    assert r["epoch"] >= 1 and r["requeues"] >= 1
    assert r["kv_leaks_clean"]


# ----------------------------------------------------------------------
# the ISSUE 14 null-honesty fix: an UNMEASURED replica must not win
# admission on a fake-perfect TTFT (r04/r05 null-when-unmeasured)
# ----------------------------------------------------------------------

def test_admission_unmeasured_ttft_is_no_signal_not_perfect(net):
    """Regression: replica 1 has the deeper queue but NO measured
    ttft/kv gauges.  The old ``value(...) or 0.0`` scored it as if it
    had perfect TTFT (6.0 < 7.5) and admitted onto the deeper queue;
    with None treated as "no signal" the scoring falls back to queue
    depth only and the shallower, fully-measured replica 0 wins."""
    from mxnet_tpu import telemetry
    if not telemetry.enabled():
        pytest.skip("telemetry off")
    telemetry.reset()
    router = _router(net, replicas=2)
    telemetry.set_gauge("serving.replica0.queue_depth", 2)
    telemetry.set_gauge("serving.replica0.ttft_ms", 3000.0)
    telemetry.set_gauge("serving.replica0.kv_block_utilization", 0.5)
    telemetry.set_gauge("serving.replica1.queue_depth", 3)
    # replica 1: ttft/kv gauges never published (no traffic measured)
    assert telemetry.value("serving.replica1.ttft_ms") is None
    req = router.submit(Request([1, 2, 3], max_new_tokens=1))
    assert router._assigned[req.id] == 0
    # the signals layer itself reports None, not 0.0
    sig = router._signals(router.replicas[1])
    assert sig["ttft_ms"] is None
    assert sig["kv_block_utilization"] is None
    telemetry.reset()


def test_replica_ttft_gauge_absent_until_measured(net):
    """Direct-read fallback + gauge publication keep the convention:
    before any finished request, load_signals reports ttft_ms=None and
    _step_replica publishes NO ttft gauge (value() stays None); the
    gauge appears only once a real TTFT was measured."""
    from mxnet_tpu import telemetry
    if not telemetry.enabled():
        pytest.skip("telemetry off")
    telemetry.reset()
    router = _router(net, replicas=2)
    rep = router.replicas[0]
    assert rep.load_signals()["ttft_ms"] is None
    router._step_replica(rep)              # idle boundary publishes...
    assert telemetry.value("serving.replica0.queue_depth") == 0
    assert telemetry.value("serving.replica0.ttft_ms") is None  # ...no ttft
    rng = np.random.RandomState(23)
    req = router.submit(Request(rng.randint(0, 64, (4,)).tolist(),
                                max_new_tokens=2))
    router.drive()
    rid = router._assigned[req.id]
    assert telemetry.value(f"serving.replica{rid}.ttft_ms") is not None
    telemetry.reset()


# ----------------------------------------------------------------------
# the ISSUE 12 small fix: typed TP rejection + recorded MeshConfig
# ----------------------------------------------------------------------

def test_engine_typed_tp_rejection_and_mesh_recorded(net):
    # a STRUCTURALLY tensor-parallel net (cfg.tensor_parallel) is still
    # typed-rejected: the engine shards plain weights itself (ISSUE 18)
    cfg = LlamaConfig(vocab_size=32, hidden_size=16, num_layers=1,
                      num_heads=2, num_kv_heads=2, intermediate_size=32,
                      tensor_parallel=True)
    with pytest.raises(NotSupportedError) as ei:
        InferenceEngine(LlamaForCausalLM(cfg))
    assert "MeshConfig" in str(ei.value)   # names the supported path
    # a pp mesh is typed-rejected; dp AND tp meshes are recorded
    with pytest.raises(NotSupportedError):
        InferenceEngine(net, mesh="dp1tp1pp2")
    eng = InferenceEngine(net, max_batch=3, block_size=8,
                          max_context=32, mesh="dp4",
                          compile_cache=_CC)
    assert eng.mesh_config.describe() == "dp4"
    assert eng.mesh_config.dp == 4
    # ISSUE 18: a tp submesh is ACCEPTED — weights sharded at rest, the
    # mesh spec in the compile-cache signature (no warmup here: init
    # must stay compile-free)
    eng2 = InferenceEngine(net, max_batch=2, block_size=8,
                           max_context=32, mesh="dp1tp2",
                           compile_cache={})
    assert eng2.mesh_config.tp == 2 and eng2.tp == 2
    assert eng2.mesh_config.describe() in \
        eng2._sig("decode", 1)


def test_lifecycle_gauges_present(net):
    """The new telemetry gauges ride the engine lifecycle."""
    from mxnet_tpu import telemetry
    if not telemetry.enabled():
        pytest.skip("telemetry off")
    telemetry.reset()
    eng = _engine(net, prefix=True)
    rng = np.random.RandomState(17)
    sp = rng.randint(0, 64, (9,)).tolist()
    assert eng.pin_prefix(sp)
    b = ContinuousBatcher(eng)
    b.submit(Request(sp + [1, 2], max_new_tokens=2))
    b.run()
    assert telemetry.value("serving.kv_blocks_in_use") is not None
    assert telemetry.value("serving.prefix_hit_rate") == 1.0
    assert telemetry.value("serving.chunk_prefill_calls") >= 1
