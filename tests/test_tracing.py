"""Causal tracing (ISSUE 14 tentpole): span trees, cross-thread
propagation, per-step phase attribution, per-request serving chains,
Chrome-trace export, and the bitwise-inert kill switch.

The acceptance gates covered here:

- every finished serving request carries a COMPLETE, correctly-parented
  span chain (admission -> queue -> prefill[chunk(s)] -> N decode
  boundaries -> finish), including a request drained and requeued
  across replicas;
- a training step's phase spans tile the step: their sum is within 10%
  of the measured step wall time on the CPU smoke;
- ``MXTPU_TRACE=0`` is bitwise-inert (fp32 params identical on/off);
- twin runs produce IDENTICAL span trees under FakeClock (deterministic
  ids + injectable clock — zero sleeps).
"""
import json
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel, telemetry
from mxnet_tpu.telemetry import tracing
from mxnet_tpu.testing.faults import FakeClock

nd = mx.nd

_CC = {}     # module-wide serving compile cache (graphs compile once)


# ----------------------------------------------------------------------
# core span semantics
# ----------------------------------------------------------------------

def test_span_nesting_ids_and_tree_shape():
    with tracing.span("root", job="r") as root:
        with tracing.span("child.a"):
            with tracing.span("leaf"):
                pass
        with tracing.span("child.b"):
            pass
    sp = {s["name"]: s for s in tracing.spans()}
    assert set(sp) == {"root", "child.a", "leaf", "child.b"}
    r = sp["root"]
    assert r["parent"] is None and r["trace"] == r["span"]
    assert sp["child.a"]["parent"] == r["span"]
    assert sp["child.b"]["parent"] == r["span"]
    assert sp["leaf"]["parent"] == sp["child.a"]["span"]
    # one trace id threads the whole tree; ids are deterministic ints
    assert {s["trace"] for s in sp.values()} == {r["span"]}
    assert r["span"] == 1                      # reset by conftest
    assert r["args"] == {"job": "r"}
    assert all(s["t1"] >= s["t0"] for s in sp.values())


def test_manual_spans_and_pretimed_records():
    root = tracing.start("request", id=42)
    mid = tracing.record("queue", 1.0, 2.0, parent=root)
    tracing.finish(root, reason="done")
    sp = {s["name"]: s for s in tracing.spans()}
    assert sp["queue"]["parent"] == root.span
    assert sp["queue"]["t0"] == 1.0 and sp["queue"]["t1"] == 2.0
    assert sp["request"]["args"] == {"id": 42, "reason": "done"}
    assert mid.trace == root.span
    # finish is idempotent; finishing None/null spans never raises
    tracing.finish(root)
    tracing.finish(None)
    assert len(tracing.spans()) == 2


def test_twin_runs_identical_trees_under_fakeclock():
    """Deterministic ids + injectable clock: two identical runs emit
    byte-identical span trees (the twin-request acceptance gate)."""
    def run():
        clock = FakeClock(100.0)
        tracing.reset()                 # fresh ids, default clock...
        tracing.configure(now=clock)    # ...then inject the FakeClock
        with tracing.span("serve"):
            clock.advance(1.0)
            req = tracing.start("request", id=7)
            clock.advance(0.5)
            tracing.record("queue", 100.0, 101.5, parent=req)
            tracing.finish(req, reason="eos")
        out = tracing.spans()
        tracing.reset()                 # restore the default clock
        return out

    a, b = run(), run()
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    assert a[0]["t0"] == 100.0                  # FakeClock stamps


def test_cross_thread_capture_activate():
    """The explicit propagation hand-shake: a span opened on a worker
    thread parents under the captured ambient trace."""
    out = {}
    with tracing.span("owner") as owner:
        ctx = tracing.capture()

        def work():
            with tracing.activate(ctx):
                with tracing.span("worker.task") as sp:
                    out["parent"] = sp.parent
                    out["trace"] = sp.trace
        t = threading.Thread(target=work)
        t.start()
        t.join()
    assert out["parent"] == owner.span
    assert out["trace"] == owner.trace
    # without activation the same work would have been a fresh root
    sp = {s["name"]: s for s in tracing.spans()}
    assert sp["worker.task"]["thread"] != sp["owner"]["thread"]


def test_kill_switch_no_spans_and_null_ops():
    tracing.configure(enabled=False)
    try:
        with tracing.span("never") as sp:
            assert sp is tracing.NULL_SPAN
        assert tracing.start("x") is tracing.NULL_SPAN
        tracing.record("y", 0.0, 1.0)
        tracing.finish(tracing.start("z"))
        assert tracing.spans() == []
        assert tracing.capture() is None
        with tracing.activate(None):
            pass
    finally:
        tracing.configure(enabled=True)
    assert tracing.spans() == []


# ----------------------------------------------------------------------
# trainer: per-step phase spans + bitwise-inert switch
# ----------------------------------------------------------------------

def _tiny_trainer(seed=1234):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.Dense(4)
    net.initialize()
    return net, parallel.DataParallelTrainer(
        net, gluon.loss.L2Loss(), "adam", {"learning_rate": 0.05})


def test_train_step_phase_spans_tile_the_step():
    """Acceptance: the phase spans' sum is within 10% of the measured
    step wall time (they tile the root span by construction)."""
    net, tr = _tiny_trainer()
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(16, 8).astype(np.float32))
    y = nd.array(rng.randn(16, 4).astype(np.float32))
    for _ in range(3):
        tr.step(x, y)
    spans = tracing.spans()
    roots = [s for s in spans if s["name"] == "train.step"]
    assert len(roots) == 3
    phases = ("train.phase.prepare", "train.phase.h2d",
              "train.phase.dispatch", "train.phase.commit")
    for root in roots:
        kids = [s for s in spans if s["parent"] == root["span"]]
        assert [k["name"] for k in kids] == list(phases)
        wall = root["t1"] - root["t0"]
        covered = sum(k["t1"] - k["t0"] for k in kids)
        assert wall > 0
        assert abs(covered - wall) <= 0.10 * wall
        # phases are contiguous and ordered
        for a, b in zip(kids, kids[1:]):
            assert b["t0"] >= a["t1"] - 1e-9
    # step_multi gets the same phase tree (one root covering K steps)
    tr2 = _tiny_trainer()[1]
    tracing.reset()
    tr2.step_multi([(x, y), (x, y)])
    spans = tracing.spans()
    roots = [s for s in spans if s["name"] == "train.step"]
    assert len(roots) == 1
    kids = [s for s in spans if s["parent"] == roots[0]["span"]]
    assert [k["name"] for k in kids] == list(phases)


def test_trace_kill_switch_is_bitwise_inert():
    rng = np.random.RandomState(3)
    xs = rng.randn(2, 16, 8).astype(np.float32)
    ys = rng.randn(2, 16, 4).astype(np.float32)
    results = {}
    for mode in (True, False):
        tracing.configure(enabled=mode)
        try:
            net, tr = _tiny_trainer()
            for i in range(2):
                tr.step(nd.array(xs[i]), nd.array(ys[i]))
            results[mode] = {
                n: p.data().asnumpy()
                for n, p in net._collect_params_with_prefix().items()}
            if not mode:
                assert tracing.spans() == []
        finally:
            tracing.configure(enabled=True)
    assert set(results[True]) == set(results[False])
    for k in results[True]:
        assert np.array_equal(results[True][k], results[False][k]), k


def test_prefetcher_worker_spans_parent_under_ambient_trace():
    """DevicePrefetcher stage spans (worker thread) land inside the
    trace that was ambient when the consumer started iterating."""
    from mxnet_tpu.io import DevicePrefetcher
    batches = [np.ones((4, 2), np.float32) * i for i in range(3)]
    with tracing.span("epoch") as root:
        pf = DevicePrefetcher(iter(batches), depth=2, mesh=None)
        got = list(pf)
        pf.close()
    assert len(got) == 3
    sp = tracing.spans()
    decodes = [s for s in sp if s["name"] == "io.decode"]
    h2ds = [s for s in sp if s["name"] == "io.h2d"]
    waits = [s for s in sp if s["name"] == "io.wait"]
    assert len(decodes) == 3 and len(h2ds) == 3 and len(waits) >= 1
    for s in decodes + h2ds:
        assert s["trace"] == root.trace
        assert s["parent"] == root.span
        assert s["thread"] != root.thread      # worker-side emission
    for s in waits:                            # consumer-side emission
        assert s["parent"] == root.span


def test_async_checkpoint_writer_span_parents_under_trace(tmp_path):
    from mxnet_tpu.checkpoint import AsyncCheckpointer
    net, _tr = _tiny_trainer()
    net(nd.array(np.zeros((2, 8), np.float32)))   # resolve deferred init
    arrays = {k: p.data() for k, p in
              net._collect_params_with_prefix().items()}
    with tracing.span("train") as root:
        ck = AsyncCheckpointer()
        ck.save(str(tmp_path / "m.params"), arrays)
        ck.wait_until_finished()
    writes = [s for s in tracing.spans()
              if s["name"] == "checkpoint.async_write"]
    assert len(writes) == 1
    assert writes[0]["parent"] == root.span
    assert writes[0]["thread"] != root.thread


# ----------------------------------------------------------------------
# serving: complete per-request chains (the acceptance criterion)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def llama():
    from mxnet_tpu.gluon.model_zoo.nlp.llama import (LlamaConfig,
                                                     LlamaForCausalLM)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=1,
                      num_heads=2, num_kv_heads=2, intermediate_size=64,
                      max_seq_len=64, tie_embeddings=True)
    net = LlamaForCausalLM(cfg)
    net.initialize()
    net(nd.array(np.zeros((1, 4), np.int32)))
    net.hybridize()
    return net


def _request_chain(spans, req):
    """The request's child spans in ring (= causal) order."""
    assert req.trace is not None
    return [s for s in spans if s["trace"] == req.trace.span]


def test_request_span_chain_complete(llama):
    from mxnet_tpu.serving import (ContinuousBatcher, InferenceEngine,
                                   Request)
    eng = InferenceEngine(llama, max_batch=2, block_size=8,
                          max_context=32, compile_cache=_CC).warmup()
    b = ContinuousBatcher(eng)
    reqs = [b.submit(Request([3, 5, 7], max_new_tokens=3)),
            b.submit(Request([11, 2], max_new_tokens=2))]
    b.run()
    spans = tracing.spans()
    for req in reqs:
        chain = _request_chain(spans, req)
        names = [s["name"] for s in chain]
        # queue -> prefill -> N decode boundaries -> the root itself
        assert names[0] == "queue"
        assert names[1] == "prefill"
        n_decode = len(req.generated) - 1      # first token from prefill
        assert names[2:2 + n_decode] == ["decode"] * n_decode
        assert names[-1] == "request"
        root = chain[-1]
        assert root["args"]["reason"] == req.finish_reason
        assert root["args"]["tokens"] == len(req.generated)
        # every hop parents on the root; the chain is time-ordered
        for s in chain[:-1]:
            assert s["parent"] == root["span"]
        for a, c in zip(chain, chain[1:-1]):
            assert c["t0"] >= a["t0"] - 1e-9


def test_chunked_prefill_chain_has_chunk_spans(llama):
    from mxnet_tpu.serving import (ContinuousBatcher, InferenceEngine,
                                   Request)
    eng = InferenceEngine(llama, max_batch=2, block_size=8,
                          max_context=32, prefill_chunk=8,
                          compile_cache=_CC).warmup()
    b = ContinuousBatcher(eng)
    # 13 prompt tokens over chunk=8 => two prefill_chunk dispatch rows
    req = b.submit(Request(list(range(1, 14)), max_new_tokens=2))
    b.run()
    chain = _request_chain(tracing.spans(), req)
    names = [s["name"] for s in chain]
    assert names.count("prefill_chunk") == 2
    assert names[0] == "queue" and names[-1] == "request"
    starts = [s["args"]["start"] for s in chain
              if s["name"] == "prefill_chunk"]
    assert starts == [0, 8]


def test_drained_request_chain_spans_replicas(llama):
    """Acceptance: a request drained off a dying replica and requeued
    keeps ONE causally-linked trace — admission x2 with a requeue hop
    between, then a complete prefill/decode chain to finish."""
    from mxnet_tpu.serving import InferenceEngine, Request, Router
    from mxnet_tpu.testing import faults

    def factory(_cc):
        return InferenceEngine(llama, max_batch=2, block_size=8,
                               max_context=32, compile_cache=_CC)

    router = Router(factory, replicas=2)
    rng = np.random.RandomState(5)
    reqs = [router.submit(Request(rng.randint(0, 64, (3,)).tolist(),
                                  max_new_tokens=3)) for _ in range(4)]
    with faults.inject("serving.replica1.step", at=2):
        router.drive()
    assert router.requeues >= 1
    spans = tracing.spans()
    moved = [r for r in reqs
             if any(s["name"] == "requeue"
                    for s in _request_chain(spans, r))]
    assert moved, "the kill must have displaced at least one request"
    for req in moved:
        chain = _request_chain(spans, req)
        names = [s["name"] for s in chain]
        admissions = [s for s in chain if s["name"] == "admission"]
        assert len(admissions) == 2
        assert admissions[0]["args"]["requeue"] is False
        assert admissions[1]["args"]["requeue"] is True
        hop = next(s for s in chain if s["name"] == "requeue")
        assert hop["args"]["from_rid"] == 1
        # the post-requeue chain still completes fully
        i_re = names.index("requeue")
        tail = names[i_re + 1:]
        assert "prefill" in tail and "decode" in tail
        assert names[-1] == "request"
        n_decode = len(req.generated) - 1
        assert tail.count("decode") == n_decode
        root = chain[-1]
        assert all(s["parent"] == root["span"] for s in chain[:-1])


# ----------------------------------------------------------------------
# export: merged Chrome-trace JSON
# ----------------------------------------------------------------------

def test_chrome_trace_merges_tracing_and_profiler_streams():
    from mxnet_tpu import profiler
    with tracing.span("step", step=1):
        pass
    # a profiler record_span only lands while a profile "runs"; drive
    # the span store directly (jax trace start is out of scope here)
    profiler._STATE["running"] = True
    try:
        profiler.record_span("pipeline:decode", 1.0, 2.0)
    finally:
        profiler._STATE["running"] = False
    payload = tracing.chrome_trace()
    evs = payload["traceEvents"]
    assert isinstance(evs, list)
    xs = [e for e in evs if e.get("ph") == "X"]
    bes = [e for e in evs if e.get("ph") in ("B", "E")]
    assert len(xs) == 1 and xs[0]["name"] == "step"
    assert xs[0]["args"]["trace"] == xs[0]["args"]["span"]
    assert xs[0]["dur"] >= 0
    assert {e["name"] for e in bes} == {"pipeline:decode"}
    for e in xs + bes:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(e)
    # valid JSON end to end (the chrome://tracing contract)
    assert json.loads(json.dumps(payload)) == payload


def test_telemetry_dump_trace_export(tmp_path, capsys):
    """tools/telemetry_dump.py --trace writes valid Chrome-trace JSON
    (the tier-1 schema smoke the satellite asks for)."""
    import tools.telemetry_dump as td
    out = tmp_path / "trace.json"
    rc = td.main(["--self-test", "--format=json", "--trace", str(out)])
    assert rc == 0
    payload = json.loads(out.read_text())
    assert "traceEvents" in payload
    xs = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in xs}
    assert {"selftest.root", "selftest.child"} <= names
    child = next(e for e in xs if e["name"] == "selftest.child")
    root = next(e for e in xs if e["name"] == "selftest.root")
    assert child["args"]["parent"] == root["args"]["span"]


def test_tracing_overhead_smoke():
    """20k no-op calls when disabled and 2k recorded spans when enabled
    both stay far under a second — the <5% step-overhead budget has
    huge headroom at the per-span cost this asserts."""
    import time
    tracing.configure(enabled=False)
    try:
        t0 = time.perf_counter()
        for _ in range(20000):
            tracing.record("x", 0.0, 1.0)
        assert time.perf_counter() - t0 < 1.0
    finally:
        tracing.configure(enabled=True)
    t0 = time.perf_counter()
    for _ in range(2000):
        tracing.record("x", 0.0, 1.0)
    assert time.perf_counter() - t0 < 1.0
