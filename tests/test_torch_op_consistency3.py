"""Third torch-oracle batch: LRN, InstanceNorm, activation families,
sequence ops, op-level Deconvolution."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from mxnet_tpu import nd

RNG = np.random.RandomState(13)


def test_lrn_matches_torch():
    x = RNG.rand(2, 7, 5, 5).astype(np.float32) + 0.1
    got = nd.LRN(nd.array(x), nsize=5, alpha=1e-4, beta=0.75,
                 knorm=2.0).asnumpy()
    want = torch.nn.functional.local_response_norm(
        torch.from_numpy(x), size=5, alpha=1e-4, beta=0.75, k=2.0).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_instance_norm_matches_torch():
    x = RNG.randn(3, 4, 6, 5).astype(np.float32)
    g = RNG.rand(4).astype(np.float32) + 0.5
    b = RNG.randn(4).astype(np.float32)
    got = nd.InstanceNorm(nd.array(x), nd.array(g), nd.array(b),
                          eps=1e-5).asnumpy()
    want = torch.nn.functional.instance_norm(
        torch.from_numpy(x), weight=torch.from_numpy(g),
        bias=torch.from_numpy(b), eps=1e-5).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_activation_families_match_torch():
    x = RNG.randn(3, 8).astype(np.float32)
    tx = torch.from_numpy(x)
    np.testing.assert_allclose(
        nd.LeakyReLU(nd.array(x), act_type="leaky", slope=0.2).asnumpy(),
        torch.nn.functional.leaky_relu(tx, 0.2).numpy(), rtol=1e-6)
    np.testing.assert_allclose(
        nd.LeakyReLU(nd.array(x), act_type="elu", slope=1.0).asnumpy(),
        torch.nn.functional.elu(tx, 1.0).numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        nd.LeakyReLU(nd.array(x), act_type="selu").asnumpy(),
        torch.nn.functional.selu(tx).numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        nd.LeakyReLU(nd.array(x), act_type="gelu").asnumpy(),
        torch.nn.functional.gelu(tx).numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        nd.Activation(nd.array(x), act_type="softrelu").asnumpy(),
        torch.nn.functional.softplus(tx).numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        nd.hard_sigmoid(nd.array(x)).asnumpy(),
        torch.clamp(tx * 0.2 + 0.5, 0, 1).numpy(),   # reference alpha=0.2
        rtol=1e-5, atol=1e-6)


def test_sequence_ops_match_manual():
    x = RNG.randn(6, 3, 4).astype(np.float32)      # (T, B, C)
    lens = np.array([2.0, 6.0, 4.0], np.float32)
    got = nd.SequenceMask(nd.array(x), nd.array(lens),
                          use_sequence_length=True, value=-1.0).asnumpy()
    want = x.copy()
    for b, L in enumerate(lens.astype(int)):
        want[L:, b, :] = -1.0
    np.testing.assert_allclose(got, want, rtol=1e-6)

    got = nd.SequenceLast(nd.array(x), nd.array(lens),
                          use_sequence_length=True).asnumpy()
    want = np.stack([x[int(L) - 1, b] for b, L in enumerate(lens)])
    np.testing.assert_allclose(got, want, rtol=1e-6)

    got = nd.SequenceReverse(nd.array(x), nd.array(lens),
                             use_sequence_length=True).asnumpy()
    want = x.copy()
    for b, L in enumerate(lens.astype(int)):
        want[:L, b, :] = x[:L, b, :][::-1]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_deconvolution_op_matches_torch():
    x = RNG.randn(2, 3, 6, 6).astype(np.float32)
    w = RNG.randn(3, 4, 4, 4).astype(np.float32)
    got = nd.Deconvolution(nd.array(x), nd.array(w), None, kernel=(4, 4),
                          num_filter=4, stride=(2, 2), pad=(1, 1),
                          adj=(0, 0), no_bias=True).asnumpy()
    want = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x), torch.from_numpy(w), stride=2,
        padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    # grouped deconvolution
    wg = RNG.randn(4, 2, 3, 3).astype(np.float32)
    xg = RNG.randn(2, 4, 5, 5).astype(np.float32)
    got = nd.Deconvolution(nd.array(xg), nd.array(wg), None, kernel=(3, 3),
                          num_filter=4, num_group=2, pad=(1, 1),
                          no_bias=True).asnumpy()
    want = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(xg), torch.from_numpy(wg), padding=1,
        groups=2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    # dilation threads through (review finding: it was silently ignored)
    wd = RNG.randn(3, 4, 3, 3).astype(np.float32)
    got = nd.Deconvolution(nd.array(x), nd.array(wd), None, kernel=(3, 3),
                          num_filter=4, stride=(2, 2), pad=(1, 1),
                          dilate=(2, 2), no_bias=True).asnumpy()
    want = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x), torch.from_numpy(wd), stride=2, padding=1,
        dilation=2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    # target_shape overrides adj to hit the exact output size
    got = nd.Deconvolution(nd.array(x), nd.array(w), None, kernel=(4, 4),
                          num_filter=4, stride=(2, 2), pad=(1, 1),
                          target_shape=(13, 13), no_bias=True).asnumpy()
    assert got.shape == (2, 4, 13, 13)
    want = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x), torch.from_numpy(w), stride=2, padding=1,
        output_padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
