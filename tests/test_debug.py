"""Debug/determinism switches (SURVEY §5.2, §5.6): MXTPU_DEBUG_NANS names
the failing op; MXTPU_ENFORCE_DETERMINISM makes two seeded runs
bit-identical end-to-end (sampler order + augmenters + init + updates).

Both flags are read at import, so each scenario runs in a subprocess."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env(**extra):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("MXTPU_", "JAX_DEBUG"))}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and ".axon_site" not in p] + [REPO])
    env.update(extra)
    return env


def _run(code, **extra):
    return subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=300, env=_env(**extra))


def test_debug_nans_names_forward_op():
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "nd.log(nd.array([-1.0])).asnumpy()\n",
        MXTPU_DEBUG_NANS="1")
    assert r.returncode != 0
    assert "MXNetError" in r.stderr
    assert "log" in r.stderr and "MXTPU_DEBUG_NANS" in r.stderr


def test_debug_nans_names_backward_op():
    # forward is finite, backward of sqrt at 0 is inf -> must name the op.
    # inf-checking is a separate opt-in (models carry intentional -inf in
    # attention masks), hence MXTPU_DEBUG_INFS here.
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd, autograd\n"
        "x = nd.array([0.0]); x.attach_grad()\n"
        "with autograd.record():\n"
        "    y = nd.sqrt(x)\n"
        "y.backward()\n",
        MXTPU_DEBUG_INFS="1")
    assert r.returncode != 0
    assert "MXNetError" in r.stderr
    assert "sqrt" in r.stderr and "MXTPU_DEBUG_NANS" in r.stderr


def test_debug_nans_off_by_default():
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "import numpy as np\n"
        "v = nd.log(nd.array([-1.0])).asnumpy()\n"
        "assert np.isnan(v).all()\n")
    assert r.returncode == 0, r.stderr


_DET_SCRIPT = """
import hashlib
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data.vision import transforms

mx.random.seed(7)

class Tiny(gluon.data.Dataset):
    def __init__(self):
        rng = np.random.RandomState(0)
        self._x = rng.rand(48, 8, 8, 1).astype(np.float32)
        self._y = rng.randint(0, 4, size=(48,))
    def __len__(self):
        return len(self._x)
    def __getitem__(self, i):
        return self._t(nd.array(self._x[i])), self._y[i]

t = transforms.Compose([transforms.RandomFlipLeftRight(),
                        transforms.ToTensor()])
ds = Tiny(); ds._t = t
loader = gluon.data.DataLoader(ds, batch_size=8, shuffle=True,
                               num_workers=2)
net = nn.Sequential()
net.add(nn.Flatten(), nn.Dense(16, activation="relu"), nn.Dense(4))
net.initialize(init=mx.init.Xavier())
trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
for epoch in range(2):
    for data, label in loader:
        with autograd.record():
            loss = loss_fn(net(data), nd.array(label))
        loss.backward()
        trainer.step(8)
h = hashlib.sha256()
for k in sorted(net.collect_params()):
    h.update(net.collect_params()[k].data().asnumpy().tobytes())
print("PARAMS", h.hexdigest())
"""


def test_enforce_determinism_two_runs_bit_identical():
    outs = []
    for _ in range(2):
        r = _run(_DET_SCRIPT, MXTPU_ENFORCE_DETERMINISM="1")
        assert r.returncode == 0, r.stderr
        line = [l for l in r.stdout.splitlines() if l.startswith("PARAMS")]
        assert line, r.stdout
        outs.append(line[0])
    assert outs[0] == outs[1]


def test_mxtpu_seed_env_seeds_global_rng():
    code = ("import mxnet_tpu as mx\n"
            "from mxnet_tpu import nd\n"
            "print('V', nd.random.uniform(shape=(3,)).asnumpy().tolist())\n")
    r1 = _run(code, MXTPU_SEED="123")
    r2 = _run(code, MXTPU_SEED="123")
    r3 = _run(code, MXTPU_SEED="124")
    assert r1.returncode == r2.returncode == r3.returncode == 0, \
        r1.stderr + r2.stderr + r3.stderr
    assert r1.stdout == r2.stdout
    assert r1.stdout != r3.stdout


def test_debug_nans_tolerates_intentional_neg_inf():
    # attention masking uses -inf; NaN-mode alone must not flag it
    r = _run(
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "import jax.numpy as jnp\n"
        "s = nd.array([[1.0, 2.0], [3.0, 4.0]])\n"
        "m = nd.array([[1.0, 0.0], [1.0, 1.0]])\n"
        "masked = nd.where(m, s, nd.full((2, 2), -jnp.inf))\n"
        "out = nd.softmax(masked).asnumpy()\n"
        "assert out[0, 1] == 0.0\n",
        MXTPU_DEBUG_NANS="1")
    assert r.returncode == 0, r.stderr
