"""IO iterators, RecordIO, image transforms
(reference: tests/python/unittest/test_io.py, test_image.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, image

nd = mx.nd


def test_ndarray_iter_basic():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    labels = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(data, labels, batch_size=4, shuffle=False)
    batches = list(it)
    assert len(batches) == 3       # 10/4 -> 2 full + 1 padded
    assert batches[0].data[0].shape == (4, 4)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4])
    assert batches[2].pad == 2
    it.reset()
    assert len(list(it)) == 3      # reset re-iterates


def test_ndarray_iter_shuffle_covers_all():
    data = np.arange(12, dtype=np.float32).reshape(12, 1)
    it = mx.io.NDArrayIter(data, None, batch_size=4, shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert sorted(seen.tolist()) == list(range(12))


def test_csv_iter(tmp_path):
    f = tmp_path / "data.csv"
    arr = np.arange(20, dtype=np.float32).reshape(5, 4)
    np.savetxt(f, arr, delimiter=",")
    it = mx.io.CSVIter(data_csv=str(f), data_shape=(4,), batch_size=2)
    batches = list(it)
    assert batches[0].data[0].shape == (2, 4)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), arr[:2])


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "x.rec")
    rec = mx.recordio.MXRecordIO(path, "w")
    for i in range(5):
        rec.write(bytes([i]) * (10 + i))
    rec.close()
    rec = mx.recordio.MXRecordIO(path, "r")
    for i in range(5):
        blob = rec.read()
        assert blob == bytes([i]) * (10 + i)
    assert rec.read() is None
    rec.close()


def test_indexed_recordio_seek(tmp_path):
    rec = mx.recordio.MXIndexedRecordIO(str(tmp_path / "x.idx"),
                                        str(tmp_path / "x.rec"), "w")
    for i in range(4):
        header = mx.recordio.IRHeader(0, float(i), i, 0)
        rec.write_idx(i, mx.recordio.pack(header, bytes([i]) * 8))
    rec.close()
    rec = mx.recordio.MXIndexedRecordIO(str(tmp_path / "x.idx"),
                                        str(tmp_path / "x.rec"), "r")
    header, blob = mx.recordio.unpack(rec.read_idx(2))
    assert header.label == 2.0
    assert blob == bytes([2]) * 8
    rec.close()


def test_image_resize_crop_normalize():
    src = nd.array(np.random.RandomState(0).uniform(
        0, 255, (32, 48, 3)).astype(np.float32))
    out = image.imresize(src, 16, 8)
    assert out.shape == (8, 16, 3)
    short = image.resize_short(src, 16)
    assert min(short.shape[:2]) == 16
    crop, _ = image.center_crop(src, (20, 10))
    assert crop.shape == (10, 20, 3)
    norm = image.color_normalize(src / 255.0, mx.nd.array([0.5, 0.5, 0.5]),
                                 mx.nd.array([0.2, 0.2, 0.2]))
    assert abs(float(norm.asnumpy().mean())) < 2.0


def test_gluon_transforms_pipeline():
    from mxnet_tpu.gluon.data.vision import transforms
    t = transforms.Compose([
        transforms.Resize(16),
        transforms.CenterCrop(12),
        transforms.ToTensor(),
        transforms.Normalize(0.5, 0.25),
    ])
    img = nd.array(np.random.RandomState(0).uniform(
        0, 255, (20, 24, 3)).astype(np.uint8))
    out = t(img)
    assert out.shape == (3, 12, 12)       # CHW after ToTensor
    assert out.asnumpy().min() < 0        # normalized


def test_dataloader_batching_and_lastbatch():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    ds = ArrayDataset(nd.array(np.arange(10, dtype=np.float32)
                               .reshape(10, 1)),
                      nd.array(np.arange(10, dtype=np.float32)))
    dl = DataLoader(ds, batch_size=4, last_batch="keep")
    shapes = [d.shape[0] for d, _ in dl]
    assert shapes == [4, 4, 2]
    dl = DataLoader(ds, batch_size=4, last_batch="discard")
    assert [d.shape[0] for d, _ in dl] == [4, 4]


def test_vision_datasets_synthetic():
    os.environ["MXTPU_SYNTHETIC_DATA"] = "1"
    from mxnet_tpu.gluon.data.vision import MNIST
    ds = MNIST(train=False)
    x, y = ds[0]
    assert x.shape == (28, 28, 1)
    assert 0 <= int(y) < 10


def test_image_augmenters_list():
    augs = image.CreateAugmenter((3, 24, 24), resize=26, rand_crop=True,
                                 rand_mirror=True, mean=True, std=True)
    assert len(augs) >= 3
    src = nd.array(np.random.RandomState(0).uniform(
        0, 255, (30, 30, 3)).astype(np.float32))
    for aug in augs:
        src = aug(src)
    assert src.shape[2] == 3


def test_color_jitter_transforms():
    """Reference gluon/data/vision/transforms.py color-jitter family."""
    from mxnet_tpu.gluon.data.vision import transforms as T
    rng = np.random.RandomState(0)
    img = mx.nd.array(rng.randint(0, 255, (8, 8, 3)).astype(np.float32))
    for t in (T.RandomBrightness(0.3), T.RandomContrast(0.3),
              T.RandomSaturation(0.3), T.RandomHue(0.1),
              T.RandomColorJitter(0.2, 0.2, 0.2, 0.05),
              T.RandomLighting(0.1)):
        out = t(img)
        assert out.shape == img.shape
    # zero-strength hue is identity up to the YIQ round-trip (~1/255)
    np.testing.assert_allclose(T.RandomHue(0.0)(img).asnumpy(),
                               img.asnumpy(), atol=1.5)
    # brightness scales linearly: zero image stays zero
    z = mx.nd.zeros((4, 4, 3))
    np.testing.assert_allclose(
        T.RandomBrightness(0.5)(z).asnumpy(), 0.0, atol=1e-6)


def test_color_jitter_augmenters_and_imread(tmp_path):
    """Round-4 augmenter tail: brightness/contrast/saturation/hue/gray
    jitters (reference image.*JitterAug) + imread."""
    rng = np.random.RandomState(0)
    img = mx.nd.array(rng.randint(0, 255, (8, 8, 3)).astype(np.float32))
    for aug in (mx.image.BrightnessJitterAug(0.3),
                mx.image.ContrastJitterAug(0.3),
                mx.image.SaturationJitterAug(0.3),
                mx.image.HueJitterAug(0.3)):
        out = aug(img)
        assert out.shape == img.shape
        assert np.isfinite(out.asnumpy()).all()
    gray = mx.image.RandomGrayAug(1.0)(img).asnumpy()
    np.testing.assert_allclose(gray[..., 0], gray[..., 1], rtol=1e-5)
    np.testing.assert_allclose(gray[..., 1], gray[..., 2], rtol=1e-5)
    # zero-strength jitter is identity
    np.testing.assert_allclose(
        mx.image.BrightnessJitterAug(0.0)(img).asnumpy(), img.asnumpy())
    # CreateAugmenter now wires the jitters in
    augs = mx.image.CreateAugmenter((3, 8, 8), brightness=0.1, contrast=0.1,
                                    saturation=0.1, hue=0.1, rand_gray=0.1)
    names = {type(a).__name__ for a in augs}
    assert {"BrightnessJitterAug", "ContrastJitterAug",
            "SaturationJitterAug", "HueJitterAug",
            "RandomGrayAug"} <= names
    # imread round-trips through the backend encoder
    cv2 = pytest.importorskip("cv2")   # PIL-backend envs skip this leg
    path = str(tmp_path / "img.png")
    cv2.imwrite(path, rng.randint(0, 255, (6, 6, 3)).astype(np.uint8))
    loaded = mx.image.imread(path)
    assert loaded.shape == (6, 6, 3)


def test_libsvm_iter_sparse_batches(tmp_path):
    """io.LibSVMIter (reference src/io/iter_libsvm.cc): CSR batches."""
    from mxnet_tpu.ndarray.sparse import CSRNDArray
    p = str(tmp_path / "t.libsvm")
    open(p, "w").write("1 0:1.5 3:2.0\n0 1:0.5\n1 2:3.0 3:1.0\n0 0:2.5\n")
    it = mx.io.LibSVMIter(p, data_shape=(4,), batch_size=2)
    b = next(iter(it))
    assert isinstance(b.data[0], CSRNDArray)
    np.testing.assert_allclose(np.asarray(b.data[0].asnumpy()),
                               [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    np.testing.assert_allclose(b.label[0].asnumpy(), [1, 0])
    it.reset()
    assert sum(1 for _ in it) == 2
    with pytest.raises(mx.MXNetError):
        open(p, "w").write("1 9:1.0\n")
        mx.io.LibSVMIter(p, data_shape=(4,), batch_size=1)


def test_load_and_fused_rnn_initializers():
    """init.Load + init.FusedRNN (reference initializer.py tail)."""
    d = {"arg:w": mx.nd.array([[1.0, 2], [3, 4]])}
    ld = mx.init.Load(d, default_init=mx.init.Zero())
    t = mx.nd.zeros((2, 2))
    ld("w", t)
    np.testing.assert_array_equal(t.asnumpy(), [[1, 2], [3, 4]])
    t2 = mx.nd.ones((3,))
    ld("other", t2)
    np.testing.assert_array_equal(t2.asnumpy(), [0, 0, 0])
    with pytest.raises(mx.MXNetError):
        ld("w", mx.nd.zeros((3, 3)))   # shape mismatch named clearly

    H, I = 3, 4
    n = 4 * H * I + 4 * H * H + 2 * 4 * H
    v = mx.nd.zeros((n,))
    init = mx.init.FusedRNN(mx.init.Xavier(), H, 1, "lstm",
                            forget_bias=1.0)
    init("lstm_params_weight", v)
    a = v.asnumpy()
    assert a[:4 * H * I].std() > 0
    bias = a[-2 * 4 * H:]
    np.testing.assert_array_equal(bias[H:2 * H], np.ones(H))  # forget gate
    # the initialized packed vector drives nd.RNN directly
    out = mx.nd.RNN(mx.nd.ones((2, 2, I)), v, mx.nd.zeros((1, 2, H)),
                    mx.nd.zeros((1, 2, H)), state_size=H, mode="lstm")
    assert out.shape == (2, 2, H)


def test_libsvm_iter_padding_and_label_file(tmp_path):
    """Review findings: trailing batch pads by wrapping (pad reported),
    separate label_libsvm file is honored."""
    p = str(tmp_path / "d.libsvm")
    open(p, "w").write("1 0:1.0\n2 1:2.0\n3 2:3.0\n")
    it = mx.io.LibSVMIter(p, data_shape=(4,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].pad == 0 and batches[1].pad == 1
    last = np.asarray(batches[1].data[0].asnumpy())
    np.testing.assert_allclose(last[1], [1.0, 0, 0, 0])   # wrapped row 0
    lp = str(tmp_path / "l.libsvm")
    open(lp, "w").write("9\n8\n7\n")
    it2 = mx.io.LibSVMIter(p, data_shape=(4,), batch_size=3,
                           label_libsvm=lp)
    b = next(iter(it2))
    np.testing.assert_allclose(b.label[0].asnumpy(), [9, 8, 7])
    with pytest.raises(mx.MXNetError, match="rows"):
        open(lp, "w").write("9\n8\n")
        mx.io.LibSVMIter(p, data_shape=(4,), batch_size=1, label_libsvm=lp)
