"""IO iterators, RecordIO, image transforms
(reference: tests/python/unittest/test_io.py, test_image.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, image

nd = mx.nd


def test_ndarray_iter_basic():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    labels = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(data, labels, batch_size=4, shuffle=False)
    batches = list(it)
    assert len(batches) == 3       # 10/4 -> 2 full + 1 padded
    assert batches[0].data[0].shape == (4, 4)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4])
    assert batches[2].pad == 2
    it.reset()
    assert len(list(it)) == 3      # reset re-iterates


def test_ndarray_iter_shuffle_covers_all():
    data = np.arange(12, dtype=np.float32).reshape(12, 1)
    it = mx.io.NDArrayIter(data, None, batch_size=4, shuffle=True)
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert sorted(seen.tolist()) == list(range(12))


def test_csv_iter(tmp_path):
    f = tmp_path / "data.csv"
    arr = np.arange(20, dtype=np.float32).reshape(5, 4)
    np.savetxt(f, arr, delimiter=",")
    it = mx.io.CSVIter(data_csv=str(f), data_shape=(4,), batch_size=2)
    batches = list(it)
    assert batches[0].data[0].shape == (2, 4)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), arr[:2])


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "x.rec")
    rec = mx.recordio.MXRecordIO(path, "w")
    for i in range(5):
        rec.write(bytes([i]) * (10 + i))
    rec.close()
    rec = mx.recordio.MXRecordIO(path, "r")
    for i in range(5):
        blob = rec.read()
        assert blob == bytes([i]) * (10 + i)
    assert rec.read() is None
    rec.close()


def test_indexed_recordio_seek(tmp_path):
    rec = mx.recordio.MXIndexedRecordIO(str(tmp_path / "x.idx"),
                                        str(tmp_path / "x.rec"), "w")
    for i in range(4):
        header = mx.recordio.IRHeader(0, float(i), i, 0)
        rec.write_idx(i, mx.recordio.pack(header, bytes([i]) * 8))
    rec.close()
    rec = mx.recordio.MXIndexedRecordIO(str(tmp_path / "x.idx"),
                                        str(tmp_path / "x.rec"), "r")
    header, blob = mx.recordio.unpack(rec.read_idx(2))
    assert header.label == 2.0
    assert blob == bytes([2]) * 8
    rec.close()


def test_image_resize_crop_normalize():
    src = nd.array(np.random.RandomState(0).uniform(
        0, 255, (32, 48, 3)).astype(np.float32))
    out = image.imresize(src, 16, 8)
    assert out.shape == (8, 16, 3)
    short = image.resize_short(src, 16)
    assert min(short.shape[:2]) == 16
    crop, _ = image.center_crop(src, (20, 10))
    assert crop.shape == (10, 20, 3)
    norm = image.color_normalize(src / 255.0, mx.nd.array([0.5, 0.5, 0.5]),
                                 mx.nd.array([0.2, 0.2, 0.2]))
    assert abs(float(norm.asnumpy().mean())) < 2.0


def test_gluon_transforms_pipeline():
    from mxnet_tpu.gluon.data.vision import transforms
    t = transforms.Compose([
        transforms.Resize(16),
        transforms.CenterCrop(12),
        transforms.ToTensor(),
        transforms.Normalize(0.5, 0.25),
    ])
    img = nd.array(np.random.RandomState(0).uniform(
        0, 255, (20, 24, 3)).astype(np.uint8))
    out = t(img)
    assert out.shape == (3, 12, 12)       # CHW after ToTensor
    assert out.asnumpy().min() < 0        # normalized


def test_dataloader_batching_and_lastbatch():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    ds = ArrayDataset(nd.array(np.arange(10, dtype=np.float32)
                               .reshape(10, 1)),
                      nd.array(np.arange(10, dtype=np.float32)))
    dl = DataLoader(ds, batch_size=4, last_batch="keep")
    shapes = [d.shape[0] for d, _ in dl]
    assert shapes == [4, 4, 2]
    dl = DataLoader(ds, batch_size=4, last_batch="discard")
    assert [d.shape[0] for d, _ in dl] == [4, 4]


def test_vision_datasets_synthetic():
    os.environ["MXTPU_SYNTHETIC_DATA"] = "1"
    from mxnet_tpu.gluon.data.vision import MNIST
    ds = MNIST(train=False)
    x, y = ds[0]
    assert x.shape == (28, 28, 1)
    assert 0 <= int(y) < 10


def test_image_augmenters_list():
    augs = image.CreateAugmenter((3, 24, 24), resize=26, rand_crop=True,
                                 rand_mirror=True, mean=True, std=True)
    assert len(augs) >= 3
    src = nd.array(np.random.RandomState(0).uniform(
        0, 255, (30, 30, 3)).astype(np.float32))
    for aug in augs:
        src = aug(src)
    assert src.shape[2] == 3


def test_color_jitter_transforms():
    """Reference gluon/data/vision/transforms.py color-jitter family."""
    from mxnet_tpu.gluon.data.vision import transforms as T
    rng = np.random.RandomState(0)
    img = mx.nd.array(rng.randint(0, 255, (8, 8, 3)).astype(np.float32))
    for t in (T.RandomBrightness(0.3), T.RandomContrast(0.3),
              T.RandomSaturation(0.3), T.RandomHue(0.1),
              T.RandomColorJitter(0.2, 0.2, 0.2, 0.05),
              T.RandomLighting(0.1)):
        out = t(img)
        assert out.shape == img.shape
    # zero-strength hue is identity up to the YIQ round-trip (~1/255)
    np.testing.assert_allclose(T.RandomHue(0.0)(img).asnumpy(),
                               img.asnumpy(), atol=1.5)
    # brightness scales linearly: zero image stays zero
    z = mx.nd.zeros((4, 4, 3))
    np.testing.assert_allclose(
        T.RandomBrightness(0.5)(z).asnumpy(), 0.0, atol=1e-6)
