"""linalg_* family + r3 op-registry additions (reference:
src/operator/tensor/la_op.cc tests in tests/python/unittest/test_operator.py
test_laop_*; ravel.cc; krprod.cc; bilinear_sampler.cc; ctc_loss.cc)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient, retry, with_seed)


def _spd(n, batch=(), seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(*batch, n, n).astype(np.float32)
    return a @ np.swapaxes(a, -1, -2) + n * np.eye(n, dtype=np.float32)


@with_seed()
def test_linalg_gemm():
    rng = np.random.RandomState(0)
    a = rng.randn(2, 3, 4).astype(np.float32)
    b = rng.randn(2, 4, 5).astype(np.float32)
    c = rng.randn(2, 3, 5).astype(np.float32)
    out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                         alpha=2.0, beta=0.5)
    assert_almost_equal(out.asnumpy(), 2.0 * (a @ b) + 0.5 * c, rtol=1e-5)
    out = nd.linalg_gemm(nd.array(a), nd.array(b.swapaxes(-1, -2)),
                         nd.array(c), transpose_b=True)
    assert_almost_equal(out.asnumpy(), a @ b + c, rtol=1e-5)


@with_seed()
def test_linalg_potrf_potri():
    a = _spd(4, (2,))
    l = nd.linalg_potrf(nd.array(a))
    ln = l.asnumpy()
    assert_almost_equal(ln @ np.swapaxes(ln, -1, -2), a, rtol=1e-4)
    assert np.allclose(np.triu(ln, 1), 0)   # lower factor
    inv = nd.linalg_potri(l)
    assert_almost_equal(inv.asnumpy(), np.linalg.inv(a), rtol=1e-3,
                        atol=1e-4)


@with_seed()
@retry(3)
def test_linalg_potrf_grad():
    a = _spd(3)
    check_numeric_gradient(
        lambda x: (nd.linalg_potrf(x) * nd.array(
            np.tril(np.linspace(1, 2, 9).reshape(3, 3)
                    .astype(np.float32)))).sum(),
        [nd.array(a)], rtol=5e-2, atol=1e-2)


@with_seed()
def test_linalg_trsm_trmm():
    rng = np.random.RandomState(1)
    l = np.tril(rng.rand(3, 3).astype(np.float32) + 1)
    b = rng.randn(3, 2).astype(np.float32)
    x = nd.linalg_trsm(nd.array(l), nd.array(b))
    assert_almost_equal(l @ x.asnumpy(), b, rtol=1e-4)
    x = nd.linalg_trsm(nd.array(l), nd.array(b), transpose=True)
    assert_almost_equal(l.T @ x.asnumpy(), b, rtol=1e-4)
    y = nd.linalg_trmm(nd.array(l), nd.array(b))
    assert_almost_equal(y.asnumpy(), l @ b, rtol=1e-5)


@with_seed()
def test_linalg_syrk_sumlogdiag():
    rng = np.random.RandomState(2)
    a = rng.randn(3, 4).astype(np.float32)
    assert_almost_equal(nd.linalg_syrk(nd.array(a)).asnumpy(), a @ a.T,
                        rtol=1e-5)
    assert_almost_equal(
        nd.linalg_syrk(nd.array(a), transpose=True, alpha=0.5).asnumpy(),
        0.5 * (a.T @ a), rtol=1e-5)
    spd = _spd(4)
    l = np.linalg.cholesky(spd).astype(np.float32)
    s = nd.linalg_sumlogdiag(nd.array(l)).asnumpy()
    assert_almost_equal(s, np.log(np.diag(l)).sum(), rtol=1e-5)


def test_linalg_diag_trian_roundtrip():
    rng = np.random.RandomState(3)
    a = rng.randn(2, 4, 4).astype(np.float32)
    d = nd.linalg_extractdiag(nd.array(a))
    assert_almost_equal(d.asnumpy(), np.diagonal(a, axis1=-2, axis2=-1),
                        rtol=1e-6)
    m = nd.linalg_makediag(d)
    assert_almost_equal(np.diagonal(m.asnumpy(), axis1=-2, axis2=-1),
                        d.asnumpy(), rtol=1e-6)
    t = nd.linalg_extracttrian(nd.array(a))
    back = nd.linalg_maketrian(t)
    assert_almost_equal(back.asnumpy(), np.tril(a), rtol=1e-6)


@with_seed()
def test_linalg_gelqf_syevd():
    rng = np.random.RandomState(4)
    a = rng.randn(3, 5).astype(np.float32)
    l, q = nd.linalg_gelqf(nd.array(a))
    assert_almost_equal(l.asnumpy() @ q.asnumpy(), a, rtol=1e-4, atol=1e-5)
    assert_almost_equal(q.asnumpy() @ q.asnumpy().T, np.eye(3), rtol=1e-4,
                        atol=1e-5)
    spd = _spd(4)
    u, w = nd.linalg_syevd(nd.array(spd))
    un, wn = u.asnumpy(), w.asnumpy()
    assert_almost_equal(un.T @ np.diag(wn) @ un, spd, rtol=1e-3, atol=1e-3)


def test_linalg_det_inverse_slogdet():
    a = _spd(3)
    assert_almost_equal(nd.linalg_det(nd.array(a)).asnumpy(),
                        np.linalg.det(a), rtol=1e-4)
    assert_almost_equal(nd.linalg_inverse(nd.array(a)).asnumpy(),
                        np.linalg.inv(a), rtol=1e-3, atol=1e-5)
    sign, logdet = nd.linalg_slogdet(nd.array(a))
    s, ld = np.linalg.slogdet(a)
    assert_almost_equal(sign.asnumpy(), s, rtol=1e-5)
    assert_almost_equal(logdet.asnumpy(), ld, rtol=1e-4)


# -- reshape codes ----------------------------------------------------------

def test_reshape_special_codes():
    x = nd.arange(24).reshape((2, 3, 4))
    assert nd.reshape(x, (-2,)).shape == (2, 3, 4)
    assert nd.reshape(x, (0, -2)).shape == (2, 3, 4)
    assert nd.reshape(x, (-3, 4)).shape == (6, 4)
    assert nd.reshape(x, (0, -3)).shape == (2, 12)
    assert nd.reshape(x, (-4, 1, 2, -2)).shape == (1, 2, 3, 4)
    assert nd.reshape(x, (-4, 2, -1, 0, 0)).shape == (2, 1, 3, 4)
    assert nd.reshape(x, (2, -1)).shape == (2, 12)
    # values preserved
    np.testing.assert_array_equal(
        nd.reshape(x, (-3, -2)).asnumpy(), x.asnumpy().reshape(6, 4))
    # reverse matches from the right: (8, 1, 7) reshape (-1, 0) reverse
    y = nd.zeros((8, 1, 7))
    assert nd.reshape(y, (-1, 0), reverse=True).shape == (8, 7)
    with pytest.raises(mx.MXNetError):
        nd.reshape(x, (-4, 5, 5, 0))
    with pytest.raises(mx.MXNetError):
        nd.reshape(x, (-1, -1))


# -- ravel / khatri-rao -----------------------------------------------------

def test_ravel_unravel():
    shape = (3, 4, 5)
    coords = np.array([[1, 2, 0], [2, 0, 3], [0, 1, 4]])  # (ndim, n)
    flat = nd.ravel_multi_index(nd.array(coords.astype(np.float32)), shape)
    ref = np.ravel_multi_index(tuple(coords), shape)
    np.testing.assert_array_equal(flat.asnumpy(), ref)
    back = nd.unravel_index(flat, shape)
    np.testing.assert_array_equal(back.asnumpy(), coords)


def test_khatri_rao():
    a = np.arange(6).reshape(2, 3).astype(np.float32)
    b = np.arange(9).reshape(3, 3).astype(np.float32)
    out = nd.khatri_rao(nd.array(a), nd.array(b))
    assert out.shape == (6, 3)
    ref = np.stack([np.kron(a[:, k], b[:, k]) for k in range(3)], axis=1)
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-6)


# -- spatial sampling -------------------------------------------------------

def test_grid_generator_affine_identity():
    theta = nd.array(np.tile([1, 0, 0, 0, 1, 0], (2, 1)).astype(np.float32))
    grid = nd.GridGenerator(theta, "affine", target_shape=(4, 6))
    g = grid.asnumpy()
    assert g.shape == (2, 2, 4, 6)
    np.testing.assert_allclose(g[0, 0, 0], np.linspace(-1, 1, 6), rtol=1e-5)
    np.testing.assert_allclose(g[0, 1, :, 0], np.linspace(-1, 1, 4),
                               rtol=1e-5)


def test_bilinear_sampler_identity_and_grad():
    rng = np.random.RandomState(5)
    data = rng.randn(2, 3, 5, 7).astype(np.float32)
    theta = nd.array(np.tile([1, 0, 0, 0, 1, 0], (2, 1)).astype(np.float32))
    grid = nd.GridGenerator(theta, "affine", target_shape=(5, 7))
    out = nd.BilinearSampler(nd.array(data), grid)
    assert_almost_equal(out.asnumpy(), data, rtol=1e-4, atol=1e-5)
    # torch cross-check on a random grid
    torch = pytest.importorskip("torch")
    g = rng.uniform(-1, 1, size=(2, 2, 4, 6)).astype(np.float32)
    out = nd.BilinearSampler(nd.array(data), nd.array(g))
    tg = torch.tensor(np.moveaxis(g, 1, -1))       # (B, Ho, Wo, 2)
    tout = torch.nn.functional.grid_sample(
        torch.tensor(data), tg, mode="bilinear", padding_mode="zeros",
        align_corners=True)
    assert_almost_equal(out.asnumpy(), tout.numpy(), rtol=1e-4, atol=1e-5)
    # gradient flows to both data and grid
    d = nd.array(data)
    gr = nd.array(g)
    d.attach_grad()
    gr.attach_grad()
    with autograd.record():
        loss = nd.BilinearSampler(d, gr).sum()
    loss.backward()
    assert np.abs(d.grad.asnumpy()).sum() > 0
    assert np.abs(gr.grad.asnumpy()).sum() > 0


# -- CTC loss ---------------------------------------------------------------

def test_ctc_loss_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(6)
    T, B, C, L = 10, 2, 5, 3
    acts = rng.randn(T, B, C).astype(np.float32)
    labels = rng.randint(1, C, size=(B, L)).astype(np.float32)
    loss = nd.ctc_loss(nd.array(acts), nd.array(labels))
    tacts = torch.tensor(acts).log_softmax(-1)
    tloss = torch.nn.functional.ctc_loss(
        tacts, torch.tensor(labels).long(),
        input_lengths=torch.full((B,), T, dtype=torch.long),
        target_lengths=torch.full((B,), L, dtype=torch.long),
        blank=0, reduction="none")
    assert_almost_equal(loss.asnumpy(), tloss.numpy(), rtol=1e-3)


def test_ctc_loss_label_lengths_and_grad():
    rng = np.random.RandomState(7)
    T, B, C = 8, 2, 4
    acts = nd.array(rng.randn(T, B, C).astype(np.float32))
    labels = nd.array(np.array([[1, 2, -1], [3, -1, -1]], np.float32))
    loss = nd.ctc_loss(acts, labels)
    assert loss.shape == (B,)
    acts.attach_grad()
    with autograd.record():
        out = nd.ctc_loss(acts, labels).sum()
    out.backward()
    assert np.isfinite(acts.grad.asnumpy()).all()
    assert np.abs(acts.grad.asnumpy()).sum() > 0


# -- fused multi-tensor optimizer ops --------------------------------------

def test_multi_sgd_update():
    ws = [nd.ones((3,)) * v for v in (1.0, 2.0)]
    gs = [nd.ones((3,)) * v for v in (0.5, 0.25)]
    nd.multi_sgd_update(ws[0], gs[0], ws[1], gs[1],
                        lrs=(0.1, 0.2), wds=(0.0, 0.0))
    assert_almost_equal(ws[0].asnumpy(), np.full(3, 0.95), rtol=1e-6)
    assert_almost_equal(ws[1].asnumpy(), np.full(3, 1.95), rtol=1e-6)


def test_multi_sgd_mom_matches_serial():
    rng = np.random.RandomState(8)
    w1, w2 = rng.randn(4).astype(np.float32), rng.randn(5).astype(np.float32)
    g1, g2 = rng.randn(4).astype(np.float32), rng.randn(5).astype(np.float32)
    # serial reference
    from mxnet_tpu.optimizer import SGD
    opt = SGD(learning_rate=0.1, momentum=0.9, wd=0.01, rescale_grad=1.0)
    wa, wb = nd.array(w1), nd.array(w2)
    sa, sb = opt.create_state(0, wa), opt.create_state(1, wb)
    for _ in range(3):
        opt.update(0, wa, nd.array(g1), sa)
        opt.update(1, wb, nd.array(g2), sb)
    # fused group
    fa, fb = nd.array(w1), nd.array(w2)
    ma, mb = nd.zeros((4,)), nd.zeros((5,))
    for _ in range(3):
        nd.multi_sgd_mom_update(fa, nd.array(g1), ma, fb, nd.array(g2), mb,
                                lrs=(0.1, 0.1), wds=(0.01, 0.01),
                                momentum=0.9)
    assert_almost_equal(fa.asnumpy(), wa.asnumpy(), rtol=1e-5)
    assert_almost_equal(fb.asnumpy(), wb.asnumpy(), rtol=1e-5)


def test_multi_lamb_update_runs():
    rng = np.random.RandomState(9)
    w = nd.array(rng.randn(6).astype(np.float32))
    g = nd.array(rng.randn(6).astype(np.float32))
    mean, var = nd.zeros((6,)), nd.zeros((6,))
    before = w.asnumpy().copy()
    nd.multi_lamb_update(w, g, mean, var, lrs=(0.01,), wds=(0.01,), step=1)
    after = w.asnumpy()
    assert np.abs(after - before).sum() > 0
    assert np.isfinite(after).all()


def test_linalg_trian_offsets():
    """Offset semantics are the SHIFTED triangle (q=n-|offset| rows), not
    numpy's half-plane (la_op-inl.h CopyTriangle)."""
    rng = np.random.RandomState(10)
    a = rng.randn(4, 4).astype(np.float32)
    t = nd.linalg_extracttrian(nd.array(a), offset=1)
    assert t.shape == (6,)                      # q=3 -> 3*4/2
    ref = a[np.tril_indices(3)[0], np.tril_indices(3)[1] + 1]
    assert_almost_equal(t.asnumpy(), ref, rtol=1e-6)
    back = nd.linalg_maketrian(t, offset=1)
    assert back.shape == (4, 4)
    assert_almost_equal(nd.linalg_extracttrian(back, offset=1).asnumpy(),
                        t.asnumpy(), rtol=1e-6)
    t2 = nd.linalg_extracttrian(nd.array(a), offset=-1)
    assert t2.shape == (6,)
    ref2 = a[np.tril_indices(3)[0] + 1, np.tril_indices(3)[1]]
    assert_almost_equal(t2.asnumpy(), ref2, rtol=1e-6)


def test_linalg_gemm_axis():
    rng = np.random.RandomState(11)
    a = rng.randn(3, 2, 4).astype(np.float32)   # rows on axis -3
    b = rng.randn(4, 2, 5).astype(np.float32)
    c = rng.randn(3, 2, 5).astype(np.float32)
    out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c), axis=-3)
    ref = np.einsum("ibk,kbj->ibj", a, b) + c
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-5)


def test_deformable_convolution_zero_offset_matches_conv():
    """contrib.DeformableConvolution with zero offsets == Convolution
    (reference src/operator/contrib/deformable_convolution.cc)."""
    rng = np.random.RandomState(12)
    x = nd.array(rng.randn(2, 4, 8, 8).astype(np.float32))
    w = nd.array(rng.randn(5, 4, 3, 3).astype(np.float32))
    b = nd.array(rng.randn(5).astype(np.float32))
    off = nd.zeros((2, 2 * 9, 8, 8))
    out = nd.contrib.DeformableConvolution(
        x, off, w, b, kernel=(3, 3), pad=(1, 1), num_filter=5)
    ref = nd.Convolution(x, w, b, kernel=(3, 3), pad=(1, 1), num_filter=5)
    assert_almost_equal(out.asnumpy(), ref.asnumpy(), rtol=1e-4, atol=1e-4)
    # gradients flow to data, offset and weight
    for arr in (x, off, w):
        arr.attach_grad()
    with autograd.record():
        loss = nd.contrib.DeformableConvolution(
            x, off, w, b, kernel=(3, 3), pad=(1, 1), num_filter=5).sum()
    loss.backward()
    assert np.abs(x.grad.asnumpy()).sum() > 0
    assert np.abs(w.grad.asnumpy()).sum() > 0
    assert np.isfinite(off.grad.asnumpy()).all()
