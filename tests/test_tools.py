"""tools/: launcher, im2rec, bandwidth (reference: tools/ +
tests/nightly/dist_sync_kvstore.py run through launch.py --launcher local)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_env():
    """Subprocess env: CPU jax, no axon sitecustomize (see conftest.py)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and ".axon_site" not in p] + [REPO])
    return env


def test_im2rec_roundtrip(tmp_path):
    # fake "images": raw bytes are packed as-is (--pass-through semantics)
    root = tmp_path / "data"
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(3):
            (d / f"{i}.jpg").write_bytes(bytes([i]) * 100)
    prefix = str(tmp_path / "set")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2rec.py"),
         prefix, str(root), "--list"], capture_output=True, text=True,
        env=_cpu_env())
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".lst")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2rec.py"),
         prefix + ".lst", str(root)], capture_output=True, text=True,
        env=_cpu_env())
    assert r.returncode == 0, r.stderr
    rec = mx.recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(rec.keys) == 6
    header, blob = mx.recordio.unpack(rec.read_idx(rec.keys[0]))
    assert len(blob) == 100
    rec.close()


def test_bandwidth_measure_runs():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bandwidth",
                                      "measure.py"),
         "--data-mb", "1", "--iters", "2", "--warmup", "1",
         "--num-keys", "2"],
        capture_output=True, text=True, env=_cpu_env())
    assert r.returncode == 0, r.stderr
    assert "GB/s" in r.stdout


@pytest.mark.slow
def test_launch_local_dist_kvstore(tmp_path):
    """The reference nightly dist test: N local processes, dist_sync
    pushpull sums across workers."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_sync')\n"
        "rank, size = kv.rank, kv.num_workers\n"
        "assert size == 2, size\n"
        "v = mx.nd.ones((4,)) * (rank + 1)\n"
        "kv.init('w', mx.nd.zeros((4,)))\n"
        "kv.pushpull('w', v, out=v)\n"
        "np.testing.assert_allclose(v.asnumpy(), 3.0 * np.ones(4))\n"
        "assert kv._wire_mode == 'allreduce', kv._wire_mode  # in-graph path\n"
        "kv.barrier()\n"
        "print('WORKER_OK', rank)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("WORKER_OK") == 2, r.stdout + r.stderr


@pytest.mark.slow
def test_launch_local_dist_async(tmp_path):
    """True dist_async (r2 missing #3): server-side optimizer applied per
    push with NO step barrier; workers push at DIFFERENT rates and the
    final weight reflects every (stale) gradient."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_async')\n"
        "assert kv.type == 'dist_async'\n"
        "rank = kv.rank\n"
        "if rank == 0:\n"
        "    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))\n"
        "kv.init('w', mx.nd.ones((4,)))   # barriers after worker-0 init\n"
        "for _ in range(10 if rank == 0 else 5):\n"
        "    kv.push('w', mx.nd.ones((4,)))   # async apply, no waiting\n"
        "kv.barrier()\n"
        "w = mx.nd.zeros((4,))\n"
        "kv.pull('w', out=w)\n"
        "np.testing.assert_allclose(w.asnumpy(), -0.5 * np.ones(4),\n"
        "                           rtol=1e-5)   # 1 - 0.1*15\n"
        "assert kv.push_stats()['w'] == 15\n"
        "print('ASYNC_OK', rank)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("ASYNC_OK") == 2, r.stdout + r.stderr


@pytest.mark.slow   # 2-process launch; the int8 wire math is gated
# fast in test_kvstore.py
def test_launch_local_dist_int8_compression(tmp_path):
    """2-process dist_sync with EQuARX-style int8 wire compression: the
    cross-worker sum matches within the per-block quantization bound."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_sync')\n"
        "rank, size = kv.rank, kv.num_workers\n"
        "assert size == 2, size\n"
        "kv.set_gradient_compression({'type': 'int8'})\n"
        "g = np.linspace(-1, 1, 600).astype(np.float32) * (rank + 1)\n"
        "kv.init('w', mx.nd.zeros((600,)))\n"
        "v = mx.nd.array(g)\n"
        "kv.pushpull('w', v, out=v)\n"
        "expect = np.linspace(-1, 1, 600) * 3.0\n"
        "np.testing.assert_allclose(v.asnumpy(), expect, atol=3 / 127.0)\n"
        "kv.barrier()\n"
        "print('WORKER_OK', rank)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("WORKER_OK") == 2, r.stdout + r.stderr


def test_dist_async_sharded_servers(tmp_path):
    """VERDICT r3 #8: launch.py -s 2 runs two dedicated server processes;
    keys hash across both (crc32), the binary typed protocol carries
    everything (no pickle on the wire), and the server-side optimizer
    applies on whichever server owns the key."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_async')\n"
        "assert len(kv._clients) == 2, len(kv._clients)\n"
        "kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))\n"
        "keys = [f'w{i}' for i in range(8)]\n"
        "for k in keys:\n"
        "    kv.init(k, mx.nd.ones((3,)))\n"
        "for k in keys:\n"
        "    kv.push(k, mx.nd.ones((3,)))\n"
        "for k in keys:\n"
        "    out = mx.nd.zeros((3,))\n"
        "    kv.pull(k, out=out)\n"
        "    np.testing.assert_allclose(out.asnumpy(), 0.9 * np.ones(3),\n"
        "                               rtol=1e-5)\n"
        "per = kv.per_server_stats()\n"
        "assert len(per) == 2\n"
        "assert all(len(s) > 0 for s in per), per   # both servers own keys\n"
        "assert sum(sum(s.values()) for s in per) == 8\n"
        "from mxnet_tpu.kvstore.ps_server import key_to_server\n"
        "for k in keys:\n"
        "    sid = key_to_server(k, 2)\n"
        "    assert k in per[sid] and k not in per[1 - sid]\n"
        "print('SHARDED_OK')\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "1", "-s", "2", "--launcher", "local",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr + r.stdout
    assert "SHARDED_OK" in r.stdout, r.stdout + r.stderr


def test_ps_wire_protocol_is_binary_typed():
    """No pickle anywhere in the PS wire path (VERDICT r3 weak #7: pickled
    frames are arbitrary-code-execution if the port is reachable)."""
    src = open(os.path.join(REPO, "mxnet_tpu", "kvstore",
                            "ps_server.py")).read()
    for needle in ("import pickle", "pickle.loads", "pickle.dumps",
                   "cPickle", "marshal", "eval(", "exec("):
        assert needle not in src, needle
    # optimizer travels as typed JSON config, reconstructed via the
    # registry — round-trip preserves hyper-parameters
    from mxnet_tpu.kvstore.ps_server import (
        _serialize_optimizer_conf, _deserialize_optimizer_conf)
    opt = mx.optimizer.SGD(learning_rate=0.25, momentum=0.9, wd=1e-4)
    back = _deserialize_optimizer_conf(_serialize_optimizer_conf(opt))
    assert type(back).__name__ == "SGD"
    assert back.lr == 0.25 and back.momentum == 0.9 and back.wd == 1e-4
    # a non-data optimizer config is refused, not silently pickled
    bad = mx.optimizer.SGD(learning_rate=0.1)
    bad.weird = object()
    with pytest.raises(mx.MXNetError, match="JSON"):
        _serialize_optimizer_conf(bad)


def test_ps_wire_bfloat16_roundtrip():
    """bf16 (the headline TPU dtype) must survive the binary wire."""
    import numpy as _onp
    import ml_dtypes
    from mxnet_tpu.kvstore.ps_server import _pack_tensor, _unpack_tensor
    a = _onp.arange(6, dtype=_onp.float32).reshape(2, 3) \
        .astype(ml_dtypes.bfloat16)
    back, _ = _unpack_tensor(_pack_tensor(a), 0)
    assert back.dtype == ml_dtypes.bfloat16
    _onp.testing.assert_array_equal(back.astype(_onp.float32),
                                    a.astype(_onp.float32))


def test_launch_ssh_emits_server_role_lines(tmp_path):
    hosts = tmp_path / "hosts"
    hosts.write_text("hostA\nhostB\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "-s", "2", "--launcher", "ssh", "-H", str(hosts),
         "python", "train.py"],
        capture_output=True, text=True, timeout=60, env=_cpu_env())
    assert r.returncode == 0, r.stderr
    assert r.stdout.count("DMLC_ROLE=server") == 2, r.stdout
    assert r.stdout.count("mxnet_tpu.kvstore.ps_server") == 2
    assert r.stdout.count("MXTPU_PS_ADDRS=") == 4   # servers + workers


def test_dist_async_send_command_retunes_server_lr(tmp_path):
    """send_command_to_servers(0, 'lr:x') reaches the server optimizer
    (reference ps-lite kController use)."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_async')\n"
        "kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))\n"
        "kv.init('w', mx.nd.ones((2,)))\n"
        "kv.push('w', mx.nd.ones((2,)))    # lr 0.1 -> w = 0.9\n"
        "kv.send_command_to_servers(0, 'lr:0.5')\n"
        "kv.push('w', mx.nd.ones((2,)))    # lr 0.5 -> w = 0.4\n"
        "out = mx.nd.zeros((2,))\n"
        "kv.pull('w', out=out)\n"
        "np.testing.assert_allclose(out.asnumpy(), [0.4, 0.4], rtol=1e-5)\n"
        "log = kv._clients[0].command_log()\n"
        "assert log == [[0, 'lr:0.5']], log\n"
        "print('CMD_OK')\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "1", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr + r.stdout
    assert "CMD_OK" in r.stdout, r.stdout + r.stderr


@pytest.mark.slow
def test_ps_heartbeat_detects_sigkilled_worker(tmp_path):
    """Failure detection (reference ps-lite PS_HEARTBEAT_TIMEOUT,
    SURVEY §5.3): 3 workers beat the server; one is SIGKILLed. The
    server must declare the silent rank dead and log it, dist_async
    push/pull must keep serving the survivors (async degrade), and a
    barrier must abort with a clean MXNetError naming the dead rank
    instead of hanging."""
    import signal
    import socket
    import time
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.kvstore.ps_server import PSServer, PSClient

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    srv = PSServer("127.0.0.1", port, num_workers=3,
                   heartbeat_timeout=1.5)
    c0 = PSClient("127.0.0.1", port)
    c0.start_heartbeat(0, interval=0.3)
    c1 = PSClient("127.0.0.1", port)
    c1.start_heartbeat(1, interval=0.3)
    c0.init("w", np.ones(4, np.float32))

    # rank 2 is a real process we SIGKILL mid-beat
    script = tmp_path / "rank2.py"
    script.write_text(
        "import sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from mxnet_tpu.kvstore.ps_server import PSClient\n"
        f"c = PSClient('127.0.0.1', {port})\n"
        "c.start_heartbeat(2, interval=0.3)\n"
        "print('BEATING', flush=True)\n"
        "time.sleep(120)\n")
    p = subprocess.Popen([sys.executable, str(script)],
                         stdout=subprocess.PIPE, text=True, env=_cpu_env())
    try:
        assert p.stdout.readline().strip() == "BEATING"
        deadline = time.time() + 15
        while time.time() < deadline:
            if "2" in c0.health()["alive"]:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"rank 2 never beat: {c0.health()}")

        p.send_signal(signal.SIGKILL)
        p.wait(timeout=10)
        deadline = time.time() + 20
        while time.time() < deadline:
            if c0.health()["dead"] == [2]:
                break
            time.sleep(0.3)
        else:
            raise AssertionError(
                f"rank 2 never declared dead: {c0.health()}")

        # async degrade: survivors keep pushing/pulling
        c1.push("w", np.ones(4, np.float32))
        np.testing.assert_allclose(c0.pull("w"),
                                   2.0 * np.ones(4, np.float32))
        # barrier aborts cleanly, naming the dead rank
        with pytest.raises(MXNetError, match=r"rank\(s\) \[2\]"):
            c0.barrier()
        assert "2" not in c0.health()["alive"]
    finally:
        if p.poll() is None:
            p.kill()
        for c in (c0, c1):
            c.close()
        srv._sock.close()


# ---------------------------------------------------------------------
# Scaling projection (tools/scaling_efficiency.py): the analytic
# 8->256-chip roofline the bench attaches as `scaling_projection`
# (reference metric: BASELINE >=70% scaling efficiency 8->256).
# ---------------------------------------------------------------------

def _project(**kw):
    from tools.scaling_efficiency import project_ici_scaling
    return project_ici_scaling(60.0, 51_114_064, **kw)


def test_scaling_projection_ici_only():
    out = _project()
    effs = {r["chips"]: r["projected_efficiency"]
            for r in out["projection"]}
    # inside one ICI domain: comm ~1ms vs 60ms step -> >95% and
    # monotonically non-increasing in N
    assert effs[8] > 0.95 and effs[256] > 0.95
    assert effs[8] >= effs[64] >= effs[256]
    assert "host_fed_efficiency" not in out["projection"][0]
    for r in out["projection"]:
        if r["chips"] <= 256:
            assert "t_dcn_ms" not in r


def test_scaling_projection_dcn_term_charges_past_one_slice():
    out = _project(chips=(256, 512, 1024))
    rows = {r["chips"]: r for r in out["projection"]}
    assert "t_dcn_ms" not in rows[256]          # one v5e slice: ICI only
    assert rows[512]["dcn_slices"] == 2
    assert rows[1024]["dcn_slices"] == 4
    assert rows[512]["t_dcn_ms"] > 0
    # DCN hop strictly lowers efficiency vs the intra-slice row
    assert (rows[512]["projected_efficiency"]
            < rows[256]["projected_efficiency"])
    # 4 slices move more cross-slice bytes per host than 2 -> slower
    assert rows[1024]["t_dcn_ms"] > rows[512]["t_dcn_ms"]


def test_scaling_projection_input_feed_cap():
    # starved host: 100 img/s supply vs 4 chips x 2000 img/s demand
    out = _project(host_decode_imgs_per_sec=100.0,
                   per_chip_imgs_per_sec=2000.0, chips_per_host=4)
    cap = out["inputs"]["input_feed_cap"]
    assert abs(cap - 100.0 / 8000.0) < 1e-9
    for r in out["projection"]:
        # host-fed row carries the cap; the ICI-only number is unchanged
        assert abs(r["host_fed_efficiency"]
                   - round(r["projected_efficiency"] * cap, 4)) < 1e-3
    # ample host (core scale-up): cap saturates at 1.0
    out2 = _project(host_decode_imgs_per_sec=100.0,
                    per_chip_imgs_per_sec=2000.0, chips_per_host=4,
                    host_core_scale=112.0)
    assert out2["inputs"]["input_feed_cap"] == 1.0


def test_bench_projection_plumbs_measured_sweep():
    import bench
    resnet = {"batch": 128, "value": 2000.0}
    rec = {"input_pipeline": {"decode_thread_sweep": [
        {"threads": 1, "img_s": 410.0}, {"threads": 4, "img_s": 410.0}]}}
    out = bench._scaling_projection(resnet, rec)
    assert "error" not in out
    assert out["inputs"]["host_decode_imgs_per_sec"] == 410.0
    assert out["inputs"]["per_chip_imgs_per_sec"] == 2000.0
    assert "input_feed_cap" in out["inputs"]
    # 512-chip row exercises the DCN term in the shipped payload
    assert any(r.get("dcn_slices") == 2 for r in out["projection"])
    # without a sweep the projection still lands, ICI-only
    out2 = bench._scaling_projection(resnet, None)
    assert "error" not in out2
    assert "input_feed_cap" not in out2["inputs"]


def test_bench_projection_host_core_slope_derates_feed_cap():
    """ISSUE 18 satellite: the host core scale-up is de-rated by the
    MEASURED thread-scaling slope (marginal img/s per added thread over
    the 1-thread img/s), computed only from in-core sweep points —
    oversubscribed points measure contention, not parallelism."""
    import bench
    resnet = {"batch": 128, "value": 2000.0}
    rec = {"input_pipeline": {"host_cores": 4, "decode_thread_sweep": [
        {"threads": 1, "img_s": 100.0}, {"threads": 2, "img_s": 190.0},
        {"threads": 4, "img_s": 340.0}, {"threads": 8, "img_s": 360.0}]}}
    out = bench._scaling_projection(resnet, rec)
    assert "error" not in out
    inp = out["inputs"]
    # slope across in-core points (1..4): (340-100)/(4-1) = 80 img/s per
    # thread; the 8-thread point (past the 4 cores) must NOT drag it
    # down to (360-100)/7
    assert inp["host_thread_slope_img_s"] == 80.0
    assert inp["host_parallel_efficiency"] == 0.8
    # core scale uses the cores recorded WITH the sweep, not this box's
    assert abs(inp["host_core_scale"] - 112.0 / 4) < 1e-9
    # supply = best * core_scale * par_eff; demand = 4 chips * 2000
    cap = inp["input_feed_cap"]
    assert abs(cap - min(1.0, 360.0 * 28.0 * 0.8 / 8000.0)) < 1e-6

    # single in-core point (1-core host): the efficiency is unmeasurable
    # and the projection DISCLOSES the linearity assumption instead of
    # silently assuming it
    rec1 = {"input_pipeline": {"host_cores": 1, "decode_thread_sweep": [
        {"threads": 1, "img_s": 410.0}, {"threads": 4, "img_s": 500.0}]}}
    out1 = bench._scaling_projection(resnet, rec1)
    assert "error" not in out1
    assert out1["inputs"]["host_parallel_efficiency"] \
        == "unmeasured: linear core scaling ASSUMED"
    assert "host_thread_slope_img_s" not in out1["inputs"]


# ----------------------------------------------------------------------
# tools/telemetry_dump.py (ISSUE 9): flight-dump/snapshot rendering +
# the live PS-server scrape path — tier-1 smoke
# ----------------------------------------------------------------------

def test_telemetry_dump_renders_flight_file(tmp_path):
    """End-to-end: take a real flight-recorder dump in-process, then
    render it with the offline tool in both formats."""
    import json as _json
    from mxnet_tpu import telemetry
    telemetry.inc("train.steps", 7)
    telemetry.set_gauge("elastic.epoch", 2)
    telemetry.observe("train.step_ms", 12.5)
    telemetry.event("unit.test", detail="smoke")
    path = telemetry.dump_flight("unit-test",
                                 path=str(tmp_path / "flight.json"))
    assert path is not None and os.path.exists(path)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_dump.py"),
         "--file", path, "--format=prom", "--events"],
        capture_output=True, text=True, timeout=120, env=_cpu_env())
    assert r.returncode == 0, r.stderr
    assert "mxtpu_train_steps 7" in r.stdout
    assert "# TYPE mxtpu_train_step_ms histogram" in r.stdout
    assert 'reason=' in r.stdout          # flight header line
    # --events appends the ring as JSONL; the last line is our event
    ev = _json.loads(r.stdout.strip().splitlines()[-1])
    assert ev["kind"] == "unit.test" and ev["v"] == 1
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_dump.py"),
         "--file", path, "--format=json"],
        capture_output=True, text=True, timeout=120, env=_cpu_env())
    assert r.returncode == 0, r.stderr
    payload = _json.loads(r.stdout)
    assert payload["reason"] == "unit-test"
    assert payload["metrics"]["counters"]["train.steps"] == 7


def test_telemetry_dump_self_test_prom():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "telemetry_dump.py"),
         "--self-test", "--format=prom"],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr
    assert "mxtpu_selftest_counter 3" in r.stdout
    assert 'mxtpu_selftest_ms_bucket{le="+Inf"} 1' in r.stdout


# ---------------------------------------------------------------------------
# tools/bench_diff.py — the cross-round perf gate (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

def _bench_payload(value=2000.0, step_ms=None, schema=1, platform="tpu"):
    d = {"metric": "resnet50_train_images_per_sec", "value": value,
         "unit": "img/s", "vs_baseline": round(value / 380.0, 3),
         "platform": platform, "telemetry_schema_version": schema,
         "batch": 128, "mfu": round(value / 8600.0, 4),
         "comm": {"collective_ms": step_ms, "est_ici_gb_s": None},
         "extra": {"serving": {"tokens_s_chip": 900.0, "p99_ms": 41.0}}}
    return d


def _write(tmp_path, name, payload):
    import json
    p = tmp_path / name
    p.write_text(json.dumps(payload))
    return str(p)


def test_bench_diff_detects_planted_regression(tmp_path):
    """The acceptance fixture pair: a planted 20% throughput regression
    must exit non-zero under --fail-on-regression 10."""
    from tools import bench_diff
    old = _write(tmp_path, "old.json", _bench_payload(value=2000.0))
    new = _write(tmp_path, "new.json", _bench_payload(value=1600.0))
    rc = bench_diff.main([old, new, "--fail-on-regression", "10",
                          "--quiet"])
    assert rc == 1
    # within threshold: clean exit
    ok = _write(tmp_path, "ok.json", _bench_payload(value=1950.0))
    assert bench_diff.main([old, ok, "--fail-on-regression", "10",
                            "--quiet"]) == 0
    # without the gate flag the same pair only reports
    assert bench_diff.main([old, new, "--quiet"]) == 0


def test_bench_diff_direction_awareness(tmp_path):
    """Latency going UP is a regression; latency going DOWN is not —
    and an improved throughput never gates."""
    from tools import bench_diff
    old = _bench_payload(); old["extra"]["serving"]["p99_ms"] = 40.0
    new = _bench_payload(); new["extra"]["serving"]["p99_ms"] = 60.0
    o = _write(tmp_path, "o.json", old)
    n = _write(tmp_path, "n.json", new)
    assert bench_diff.main([o, n, "--fail-on-regression", "10",
                            "--quiet"]) == 1
    faster = _bench_payload(value=2400.0)
    faster["extra"]["serving"]["p99_ms"] = 20.0
    f = _write(tmp_path, "f.json", faster)
    assert bench_diff.main([o, f, "--fail-on-regression", "10",
                            "--quiet"]) == 0


def test_bench_diff_disagg_field_directions(tmp_path):
    """ISSUE 18 serving fields: handoff_ms gates when it GROWS, pool
    occupancies gate when they SHRINK; tp_shards is config — a resharded
    fleet is a changed knob, never a regression."""
    from tools import bench_diff
    assert bench_diff.direction("extra.serving.handoff_ms") == "down"
    assert bench_diff.direction(
        "extra.serving.prefill_pool_occupancy") == "up"
    assert bench_diff.direction(
        "extra.serving.decode_pool_occupancy") == "up"
    old = _bench_payload()
    old["extra"]["serving"]["handoff_ms"] = 0.2
    old["extra"]["serving"]["decode_pool_occupancy"] = 0.9
    old["extra"]["serving"]["tp_shards"] = 2
    o = _write(tmp_path, "o.json", old)
    worse = _bench_payload()
    worse["extra"]["serving"]["handoff_ms"] = 0.6
    worse["extra"]["serving"]["decode_pool_occupancy"] = 0.9
    worse["extra"]["serving"]["tp_shards"] = 2
    n = _write(tmp_path, "n.json", worse)
    # handoff latency tripled -> gates
    assert bench_diff.main([o, n, "--fail-on-regression", "10",
                            "--quiet"]) == 1
    starved = _bench_payload()
    starved["extra"]["serving"]["handoff_ms"] = 0.2
    starved["extra"]["serving"]["decode_pool_occupancy"] = 0.4
    starved["extra"]["serving"]["tp_shards"] = 2
    n2 = _write(tmp_path, "n2.json", starved)
    # decode pool idling (occupancy halved) -> gates
    assert bench_diff.main([o, n2, "--fail-on-regression", "10",
                            "--quiet"]) == 1
    resharded = _bench_payload()
    resharded["extra"]["serving"]["handoff_ms"] = 0.2
    resharded["extra"]["serving"]["decode_pool_occupancy"] = 0.9
    resharded["extra"]["serving"]["tp_shards"] = 8
    n3 = _write(tmp_path, "n3.json", resharded)
    # only the tp_shards knob changed -> clean exit
    assert bench_diff.main([o, n3, "--fail-on-regression", "10",
                            "--quiet"]) == 0


def test_bench_diff_skips_nulls_and_checks_schema(tmp_path):
    from tools import bench_diff
    # null-when-unmeasured on one side: the metric never compares, so a
    # CPU round with nulls cannot fake a regression
    old = _bench_payload(step_ms=3.2)
    new = _bench_payload(step_ms=None)
    o = _write(tmp_path, "o.json", old)
    n = _write(tmp_path, "n.json", new)
    assert bench_diff.main([o, n, "--fail-on-regression", "10",
                            "--quiet"]) == 0
    # schema drift: refuse to compare (exit 2) unless allowed
    drift = _write(tmp_path, "d.json", _bench_payload(schema=2))
    assert bench_diff.main([o, drift, "--quiet"]) == 2
    assert bench_diff.main([o, drift, "--allow-schema-drift",
                            "--quiet"]) == 0


def test_bench_diff_platform_mismatch_never_gates(tmp_path):
    """A CPU-fallback round vs a TPU round is apples-to-oranges: the
    rounds 4/5 tunnel outage must not read as a 90% regression."""
    from tools import bench_diff
    o = _write(tmp_path, "o.json", _bench_payload(value=2000.0))
    n = _write(tmp_path, "n.json",
               _bench_payload(value=150.0, platform="cpu"))
    assert bench_diff.main([o, n, "--fail-on-regression", "10",
                            "--quiet"]) == 0


def test_bench_diff_reads_driver_round_wrappers(tmp_path):
    """BENCH_r*.json trajectory files ({"cmd", "parsed": ...}) unwrap;
    an unparsed round (parsed: null) compares as nothing, exit 0."""
    import json
    from tools import bench_diff
    w_old = _write(tmp_path, "BENCH_r01.json",
                   {"n": 1, "cmd": "python bench.py", "rc": 0,
                    "parsed": _bench_payload(value=2000.0)})
    w_new = _write(tmp_path, "BENCH_r02.json",
                   {"n": 2, "cmd": "python bench.py", "rc": 0,
                    "parsed": _bench_payload(value=1000.0)})
    assert bench_diff.main([w_old, w_new, "--fail-on-regression", "10",
                            "--quiet"]) == 1
    w_null = _write(tmp_path, "BENCH_r03.json",
                    {"n": 3, "cmd": "python bench.py", "rc": 1,
                     "parsed": None})
    assert bench_diff.main([w_old, w_null, "--fail-on-regression",
                            "10", "--quiet"]) == 0


def test_scaling_efficiency_3d_projection():
    """tools/scaling_efficiency.py 3D model: more chips on tp/pp axes
    cost comm/bubble efficiency; every input is surfaced; the tp term
    discloses itself when unmodeled."""
    from tools.scaling_efficiency import project_3d_scaling
    out = project_3d_scaling(
        60.0, 1.02e8,
        mesh_shapes=[(256, 1, 1), (64, 4, 1), (32, 4, 2)],
        act_bytes_per_layer=2.6e6, n_layers=50, base_mfu=0.24)
    rows = out["projection"]
    assert [r["chips"] for r in rows] == [256, 256, 256]
    assert all(0 < r["projected_efficiency"] <= 1 for r in rows)
    # pure dp pays only the (well-overlapped) grad ring
    assert rows[0]["projected_efficiency"] > rows[1]["projected_efficiency"]
    # adding a pipeline axis pays the 1F1B bubble on top
    assert rows[1]["projected_efficiency"] > rows[2]["projected_efficiency"]
    assert rows[2]["pp_bubble_frac"] > 0
    assert rows[0]["pp_bubble_frac"] == 0
    assert all("projected_mfu" in r for r in rows)
    # unmodeled tp term must say so rather than read as free
    out2 = project_3d_scaling(60.0, 1.02e8, mesh_shapes=[(64, 4, 1)])
    assert "UNMODELED" in out2["projection"][0]["tp_term"]
    assert out["inputs"]["param_bytes"] == 1.02e8
