"""tools/: launcher, im2rec, bandwidth (reference: tools/ +
tests/nightly/dist_sync_kvstore.py run through launch.py --launcher local)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_env():
    """Subprocess env: CPU jax, no axon sitecustomize (see conftest.py)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and ".axon_site" not in p] + [REPO])
    return env


def test_im2rec_roundtrip(tmp_path):
    # fake "images": raw bytes are packed as-is (--pass-through semantics)
    root = tmp_path / "data"
    for cls in ("cat", "dog"):
        d = root / cls
        d.mkdir(parents=True)
        for i in range(3):
            (d / f"{i}.jpg").write_bytes(bytes([i]) * 100)
    prefix = str(tmp_path / "set")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2rec.py"),
         prefix, str(root), "--list"], capture_output=True, text=True,
        env=_cpu_env())
    assert r.returncode == 0, r.stderr
    assert os.path.exists(prefix + ".lst")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2rec.py"),
         prefix + ".lst", str(root)], capture_output=True, text=True,
        env=_cpu_env())
    assert r.returncode == 0, r.stderr
    rec = mx.recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(rec.keys) == 6
    header, blob = mx.recordio.unpack(rec.read_idx(rec.keys[0]))
    assert len(blob) == 100
    rec.close()


def test_bandwidth_measure_runs():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bandwidth",
                                      "measure.py"),
         "--data-mb", "1", "--iters", "2", "--warmup", "1",
         "--num-keys", "2"],
        capture_output=True, text=True, env=_cpu_env())
    assert r.returncode == 0, r.stderr
    assert "GB/s" in r.stdout


@pytest.mark.slow
def test_launch_local_dist_kvstore(tmp_path):
    """The reference nightly dist test: N local processes, dist_sync
    pushpull sums across workers."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_sync')\n"
        "rank, size = kv.rank, kv.num_workers\n"
        "assert size == 2, size\n"
        "v = mx.nd.ones((4,)) * (rank + 1)\n"
        "kv.init('w', mx.nd.zeros((4,)))\n"
        "kv.pushpull('w', v, out=v)\n"
        "np.testing.assert_allclose(v.asnumpy(), 3.0 * np.ones(4))\n"
        "assert kv._wire_mode == 'allreduce', kv._wire_mode  # in-graph path\n"
        "kv.barrier()\n"
        "print('WORKER_OK', rank)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("WORKER_OK") == 2, r.stdout + r.stderr


@pytest.mark.slow
def test_launch_local_dist_async(tmp_path):
    """True dist_async (r2 missing #3): server-side optimizer applied per
    push with NO step barrier; workers push at DIFFERENT rates and the
    final weight reflects every (stale) gradient."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_async')\n"
        "assert kv.type == 'dist_async'\n"
        "rank = kv.rank\n"
        "if rank == 0:\n"
        "    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))\n"
        "kv.init('w', mx.nd.ones((4,)))   # barriers after worker-0 init\n"
        "for _ in range(10 if rank == 0 else 5):\n"
        "    kv.push('w', mx.nd.ones((4,)))   # async apply, no waiting\n"
        "kv.barrier()\n"
        "w = mx.nd.zeros((4,))\n"
        "kv.pull('w', out=w)\n"
        "np.testing.assert_allclose(w.asnumpy(), -0.5 * np.ones(4),\n"
        "                           rtol=1e-5)   # 1 - 0.1*15\n"
        "assert kv.push_stats()['w'] == 15\n"
        "print('ASYNC_OK', rank)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("ASYNC_OK") == 2, r.stdout + r.stderr


def test_launch_local_dist_int8_compression(tmp_path):
    """2-process dist_sync with EQuARX-style int8 wire compression: the
    cross-worker sum matches within the per-block quantization bound."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_sync')\n"
        "rank, size = kv.rank, kv.num_workers\n"
        "assert size == 2, size\n"
        "kv.set_gradient_compression({'type': 'int8'})\n"
        "g = np.linspace(-1, 1, 600).astype(np.float32) * (rank + 1)\n"
        "kv.init('w', mx.nd.zeros((600,)))\n"
        "v = mx.nd.array(g)\n"
        "kv.pushpull('w', v, out=v)\n"
        "expect = np.linspace(-1, 1, 600) * 3.0\n"
        "np.testing.assert_allclose(v.asnumpy(), expect, atol=3 / 127.0)\n"
        "kv.barrier()\n"
        "print('WORKER_OK', rank)\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=_cpu_env())
    assert r.returncode == 0, r.stderr + r.stdout
    assert r.stdout.count("WORKER_OK") == 2, r.stdout + r.stderr
