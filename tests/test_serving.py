"""Serving engine (ISSUE 7): paged KV cache, AOT bucketed prefill/decode,
continuous batching, int8 serving, decode-parity gates.

THE parity contract (the llama.py:56 "one source so decode parity can't
drift" promise, finally enforced): decode-with-KV-cache logits are
BITWISE equal (fp32) to the hybridized full forward evaluated at the
decode's context-bucket width (prompt padded to the bucket, logits read
at the last valid row).  The bucket-width reference is the precise
statement of what fixed-shape serving computes: XLA's reduce order
changes with the summation WIDTH (empirically: zero-padded reductions
are width-stable up to 16 elements and at equal widths, not across
different >16 widths), so the engine matches the full forward exactly
when both run at the same padded width — which is also how a batch
verifier would run the forward in production.  Against the UNPADDED
forward the logits agree to float eps and the argmax/token stream is
identical (gated below too).
"""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo.nlp.llama import (LlamaConfig,
                                                 LlamaForCausalLM)
from mxnet_tpu.serving import (ContinuousBatcher, InferenceEngine,
                               PagedKVCache, Request, StaticBatcher,
                               next_bucket, serving_block)

nd = mx.nd


def _net(tie=True, vocab=64, layers=2):
    cfg = LlamaConfig(vocab_size=vocab, hidden_size=32, num_layers=layers,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_seq_len=64, tie_embeddings=tie)
    net = LlamaForCausalLM(cfg)
    net.initialize()
    net(nd.array([[1, 2, 3]], dtype="int32"))     # materialize shapes
    net.hybridize()   # the engine mirrors ONE fused graph; the eager
    # op-by-op forward differs by fusion (FMA) — hybridized is both the
    # production path and the parity reference
    return net


def _ref_last_logits(net, tokens, width):
    """Full-forward logits at the last valid position, evaluated at the
    padded ``width`` (the decode bucket)."""
    pad = np.zeros((1, width), np.int32)
    pad[0, :len(tokens)] = tokens
    return net(nd.array(pad, dtype="int32")).asnumpy()[0, len(tokens) - 1]


def _drive(eng, slot, prompt, n_steps, check=None):
    """Prefill + n_steps greedy decode; calls check(cur, pos, logits)
    after every decode step.  Returns the generated ids."""
    tok, _ = eng.prefill(slot, prompt)
    cur = list(prompt) + [int(tok)]
    for _ in range(n_steps):
        pos = len(cur) - 1
        assert eng.reserve(slot, pos)
        nxt, lg = eng.decode([(slot, cur[-1], pos)])
        if check is not None:
            check(cur, pos, lg[0])
        cur.append(int(nxt[0]))
    return cur[len(prompt):]


# ----------------------------------------------------------------------
# paged KV cache
# ----------------------------------------------------------------------

def test_paged_cache_alloc_free_reuse():
    c = PagedKVCache(num_layers=1, num_kv_heads=2, head_dim=8,
                     num_blocks=9, block_size=4, max_batch=2)
    assert c.num_free_blocks == 8          # block 0 reserved
    assert c.alloc("a", 10)                # 3 blocks
    assert c.blocks_in_use == 3
    assert c.alloc("b", 17)                # 5 blocks
    assert c.num_free_blocks == 0
    assert not c.alloc("c", 1)             # exhausted
    assert c.alloc_failures == 1
    # grow a: needs a 4th block -> fails until b frees
    assert not c.ensure("a", 12)
    c.free("b")
    assert c.ensure("a", 12)
    assert c.blocks_in_use == 4
    # trim back to 10 tokens -> 3 blocks again, freed block reusable
    c.trim("a", 10)
    assert c.blocks_in_use == 3
    # table_array pads with the null block and respects width
    arr = c.table_array(["a", None], 4)
    assert arr.shape == (2, 4)
    assert (arr[1] == 0).all()
    assert (arr[0, :3] > 0).all() and arr[0, 3] == 0
    c.free("a")
    assert c.blocks_in_use == 0 and c.utilization() == 0.0
    # block 0 is never handed out
    assert c.alloc("d", 32)
    assert 0 not in c.table("d")


def test_cache_rejects_bad_config():
    with pytest.raises(mx.MXNetError):
        PagedKVCache(1, 2, 8, num_blocks=4, block_size=3)   # not pow2
    with pytest.raises(mx.MXNetError):
        PagedKVCache(1, 2, 8, num_blocks=1)                 # no null blk
    c = PagedKVCache(1, 2, 8, num_blocks=4, block_size=4)
    assert c.alloc("a", 4)
    with pytest.raises(mx.MXNetError):
        c.alloc("a", 4)                                     # double alloc


# ----------------------------------------------------------------------
# decode parity: THE gate
# ----------------------------------------------------------------------

@pytest.mark.parametrize("tie", [True, False])
def test_decode_parity_bitwise_per_bucket(tie):
    """Across every shape bucket (8/16/32, including the 8->16->32
    crossings), decode-with-cache logits == hybridized full forward at
    the bucket width, BITWISE in fp32, for every generated position."""
    net = _net(tie=tie)
    eng = InferenceEngine(net, max_batch=2, block_size=8, max_context=32)
    eng.warmup()
    rng = np.random.RandomState(3)
    checked = [0]

    def make_check():
        def check(cur, pos, logits):
            bucket = next_bucket(pos + 1, eng.buckets)
            ref = _ref_last_logits(net, cur, bucket)
            np.testing.assert_array_equal(
                logits, ref,
                err_msg=f"decode at pos {pos} (bucket {bucket}) is not "
                        "bitwise the full forward")
            checked[0] += 1
        return check

    # one prompt per bucket entry point; each decodes to max_context-1,
    # so the 5-token prompt crosses 8 -> 16 -> 32 inside one sequence
    for slot, t0 in enumerate((5, 9, 17)):
        prompt = rng.randint(0, 64, (t0,)).tolist()
        _drive(eng, slot, prompt, 31 - t0, check=make_check())
        eng.release(slot)
    assert checked[0] >= 60
    assert eng.stats["compiles_after_warmup"] == 0


def test_prefill_parity_bitwise_per_bucket():
    """Prefill (padded and bucket-exact prompts) reproduces the full
    forward's last-position logits bitwise, and samples its argmax."""
    net = _net(tie=False)
    eng = InferenceEngine(net, max_batch=2, block_size=8, max_context=32)
    eng.warmup()
    rng = np.random.RandomState(5)
    for slot, t0 in enumerate((3, 8, 12, 16, 25, 32)):
        prompt = rng.randint(0, 64, (t0,)).tolist()
        tok, logits = eng.prefill(slot, prompt)
        bucket = next_bucket(t0, eng.buckets)
        ref = _ref_last_logits(net, prompt, bucket)
        np.testing.assert_array_equal(logits, ref)
        assert tok == int(ref.argmax())
        eng.release(slot)


@pytest.mark.slow   # slow-marked (ISSUE 18 tier-1 headroom): the BITWISE
# per-bucket decode/prefill parity gates above stay tier-1; this is the
# float-eps-vs-unpadded + net.generate() stream twin
def test_decode_close_to_unpadded_forward_and_matches_generate():
    """User-visible guarantees vs the UNPADDED forward: logits to float
    eps and the greedy token stream identical to net.generate()."""
    net = _net(tie=True)
    eng = InferenceEngine(net, max_batch=2, block_size=8, max_context=32)
    eng.warmup()
    prompt = np.random.RandomState(0).randint(0, 64, (5,)).tolist()

    def check(cur, pos, logits):
        # every unpadded width is a fresh reference compile — 8 steps
        # cover the 8->16 bucket crossing without burning tier-1 budget
        ref = net(nd.array([cur], dtype="int32")).asnumpy()[0, -1]
        np.testing.assert_allclose(logits, ref, atol=1e-5, rtol=1e-5)
        assert int(logits.argmax()) == int(ref.argmax())

    got = _drive(eng, 0, prompt, 8, check=check)
    ref = net.generate(nd.array([prompt], dtype="int32"), 9,
                       temperature=0.0).asnumpy()[0, 5:]
    np.testing.assert_array_equal(np.asarray(got), ref)


def test_joined_batch_rows_match_single_sequence():
    """Sequences decoding JOINED in one batch produce the same logits
    rows as each would alone (batch-dim stability — continuous batching
    can't perturb a neighbour's numerics)."""
    net = _net(tie=True)
    rng = np.random.RandomState(7)
    pa = rng.randint(0, 64, (5,)).tolist()
    pb = rng.randint(0, 64, (11,)).tolist()
    # solo runs
    eng1 = InferenceEngine(net, max_batch=2, block_size=8, max_context=32)
    eng1.warmup()
    solo = {}
    for slot, p in ((0, pa), (1, pb)):
        logits_rows = []
        _drive(eng1, slot, p, 4,
               check=lambda cur, pos, lg, rows=logits_rows:
               rows.append(lg.copy()))
        solo[slot] = logits_rows
    # joined run on a fresh engine: prefill both, decode as one batch
    eng2 = InferenceEngine(net, max_batch=2, block_size=8, max_context=32)
    eng2.warmup()
    ta, _ = eng2.prefill(0, pa)
    tb, _ = eng2.prefill(1, pb)
    cura, curb = list(pa) + [int(ta)], list(pb) + [int(tb)]
    for step in range(4):
        poa, pob = len(cura) - 1, len(curb) - 1
        assert eng2.reserve(0, poa) and eng2.reserve(1, pob)
        nxt, lg = eng2.decode([(0, cura[-1], poa), (1, curb[-1], pob)])
        # NOTE the joined step runs at the max of the two context
        # buckets; row parity vs solo holds when both land in the same
        # bucket zone (<=16-stable or same bucket) — positions here stay
        # within bucket 16 for both, so rows must be bitwise
        np.testing.assert_array_equal(lg[0], solo[0][step])
        np.testing.assert_array_equal(lg[1], solo[1][step])
        cura.append(int(nxt[0]))
        curb.append(int(nxt[1]))


# ----------------------------------------------------------------------
# int8 serving (quantize_net wiring)
# ----------------------------------------------------------------------

# slow-marked (ISSUE 18 tier-1 headroom): quantize_net numerics stay
# covered by test_quantization; the engine wiring by the int8 loadgen
@pytest.mark.slow
@pytest.mark.slow   # int8 WEIGHT serving end-to-end; the int8 math is
# gated fast in test_quantization and low-precision serving in
# test_quant_kv (ISSUE 20 tier-1 headroom)
def test_int8_engine_bitwise_vs_quantized_net_and_bounded_vs_fp32():
    """int8 serving: the engine's decode mirrors QuantizedDense
    op-for-op, so parity vs the QUANTIZED net's own (bucket-width)
    forward stays BITWISE — int32 accumulation is exact — while drift
    vs the fp32 snapshot stays inside the documented bound
    (docs/SERVING.md: |logit drift| <= 0.05 * max|logit|)."""
    net = _net(tie=False)
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 64, (5,)).tolist()
    calib = [nd.array(rng.randint(0, 64, (2, 12)), dtype="int32")
             for _ in range(2)]
    fp32_ref = _ref_last_logits(net, prompt, 8)
    eng = InferenceEngine(net, max_batch=2, block_size=8, max_context=32,
                          quantize="int8", calib_data=calib)
    assert eng.quantized
    eng.warmup()
    tok, logits = eng.prefill(0, prompt)
    qref = _ref_last_logits(net, prompt, 8)      # net is now int8
    np.testing.assert_array_equal(logits, qref)
    drift = np.abs(np.asarray(logits) - fp32_ref).max()
    assert drift <= 0.05 * np.abs(fp32_ref).max()

    def check(cur, pos, lg):
        bucket = next_bucket(pos + 1, eng.buckets)
        np.testing.assert_array_equal(
            lg, _ref_last_logits(net, cur, bucket))

    _drive_from = list(prompt) + [int(tok)]
    cur = _drive_from
    for _ in range(8):
        pos = len(cur) - 1
        assert eng.reserve(0, pos)
        nxt, lg = eng.decode([(0, cur[-1], pos)])
        check(cur, pos, lg[0])
        cur.append(int(nxt[0]))
    assert eng.stats["compiles_after_warmup"] == 0


def test_engine_rejects_tp_and_bad_quantize():
    cfg = LlamaConfig(vocab_size=32, hidden_size=16, num_layers=1,
                      num_heads=2, num_kv_heads=2, intermediate_size=32,
                      tensor_parallel=True)
    with pytest.raises(mx.MXNetError):
        InferenceEngine(LlamaForCausalLM(cfg))
    net = _net()
    with pytest.raises(mx.MXNetError):
        InferenceEngine(net, quantize="int4")
    with pytest.raises(mx.MXNetError):
        InferenceEngine(net, quantize="int8")    # no calib_data


# ----------------------------------------------------------------------
# scheduler: full lifecycle, continuous vs static
# ----------------------------------------------------------------------

def test_full_request_lifecycle_slot_reuse_zero_retraces():
    """enqueue -> prefill -> joined decode -> EOS/length -> slot reuse,
    with ZERO compiles after warmup (the compile-cache counter is the
    retrace gate) and every block back in the pool at the end."""
    net = _net(tie=True, vocab=64)
    eng = InferenceEngine(net, max_batch=2, block_size=8, max_context=32)
    eng.warmup()
    # discover the token greedy decode settles on, to exercise the EOS
    # path deterministically
    rng = np.random.RandomState(2)
    probe = net.generate(nd.array([rng.randint(0, 64, (4,)).tolist()],
                                  dtype="int32"), 8,
                         temperature=0.0).asnumpy()[0]
    eos_tok = int(probe[-1])
    batcher = ContinuousBatcher(eng)
    reqs = []
    for i in range(5):   # 5 requests through 2 slots -> slots reused
        prompt = rng.randint(0, 64, (3 + 2 * i,)).tolist()
        eos = eos_tok if i == 0 else None
        reqs.append(batcher.submit(Request(prompt, max_new_tokens=6,
                                           eos_id=eos)))
    stats = batcher.run()
    assert stats["requests"] == 5
    assert all(r.done for r in reqs)
    assert reqs[0].finish_reason in ("eos", "length")
    assert any(r.finish_reason == "length" for r in reqs)
    for r in reqs:
        assert 1 <= len(r.generated) <= 6
        assert r.latency() is not None and r.ttft() is not None
    # slots fully recycled, pool drained, nothing recompiled
    assert len(batcher._free_slots) == eng.max_batch
    assert eng.cache.stats()["sequences"] == 0
    assert eng.cache.blocks_in_use == 0
    assert eng.stats["compiles_after_warmup"] == 0
    assert stats["occupancy"] > 0
    # ISSUE 12 hygiene: the refcount sweep balances (no dangling holds),
    # the in-use gauge went back to zero, and a second release of an
    # already-freed slot is the typed double-free
    assert eng.cache.check_leaks()
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import DoubleFreeError
    if telemetry.enabled():
        assert telemetry.value("serving.kv_blocks_in_use") == 0
    with pytest.raises(DoubleFreeError):
        eng.release(0)


@pytest.mark.slow
def test_continuous_beats_static_on_mixed_lengths():
    """The acceptance gate, on deterministic quantities: same request
    mix, same engine graphs — continuous batching needs FEWER decode
    steps (higher tokens/step) and holds HIGHER occupancy than static,
    because finished slots refill at token boundaries instead of idling
    until the batch drains."""
    from tools.serve_loadgen import run_loadgen
    payload = run_loadgen(n_requests=8, max_batch=3, block_size=8,
                          max_context=64, mode="both", smoke=True)
    c = payload["policies"]["continuous"]
    s = payload["policies"]["static"]
    assert c["tokens_generated"] == s["tokens_generated"]   # same work
    assert c["decode_steps"] < s["decode_steps"]
    assert c["occupancy"] > s["occupancy"]
    assert c["tokens_per_step"] > s["tokens_per_step"]
    assert c["compiles_after_warmup"] == 0
    assert s["compiles_after_warmup"] == 0
    # the serving block is the bench schema and it round-trips
    blk = payload["serving"]
    assert set(blk) >= set(serving_block())
    assert json.loads(json.dumps(payload)) == payload


def test_pool_exhaustion_keeps_requests_queued():
    """A request that can't get blocks stays queued (alloc is atomic —
    no partial allocation) and is admitted once a slot frees."""
    net = _net(tie=True)
    # pool sized so only ~one long sequence fits at a time
    eng = InferenceEngine(net, max_batch=2, block_size=8, max_context=32,
                          num_blocks=6)
    eng.warmup()
    rng = np.random.RandomState(4)
    batcher = ContinuousBatcher(eng)
    for _ in range(3):
        batcher.submit(Request(rng.randint(0, 64, (17,)).tolist(),
                               max_new_tokens=3))
    stats = batcher.run()
    assert stats["requests"] == 3
    assert eng.cache.blocks_in_use == 0
    assert eng.cache.alloc_failures > 0       # exhaustion actually hit


def test_request_finishing_inside_prefill_is_progress():
    """max_new_tokens=1 (or EOS on the prefill-sampled token) completes
    the request inside the prefill boundary; the scheduler must count
    that as progress, not a wedged queue (regression: run() raised
    'cannot be admitted' when an admitted request never reached the
    decode batch)."""
    net = _net(tie=True)
    eng = InferenceEngine(net, max_batch=2, block_size=8, max_context=16)
    eng.warmup()
    b = ContinuousBatcher(eng)
    one = b.submit(Request([5], max_new_tokens=1))
    two = b.submit(Request([1, 2], max_new_tokens=2))
    stats = b.run()
    assert stats["requests"] == 2
    assert one.finish_reason == "length" and len(one.generated) == 1
    assert len(two.generated) == 2
    # EOS hit by the very token prefill samples
    tok, _ = eng.prefill(9, [7, 8])
    eng.release(9)
    b2 = ContinuousBatcher(eng)
    r = b2.submit(Request([7, 8], max_new_tokens=5, eos_id=int(tok)))
    b2.run()
    assert r.finish_reason == "eos" and len(r.generated) == 1
    # static baseline: a whole batch finishing in prefill is legal
    s = StaticBatcher(eng)
    for _ in range(3):
        s.submit(Request([5], max_new_tokens=1))
    st = s.run()
    assert st["requests"] == 3 and st["decode_steps"] == 0
    assert eng.cache.blocks_in_use == 0


def test_prompt_longer_than_max_context_rejected():
    net = _net(tie=True)
    eng = InferenceEngine(net, max_batch=2, block_size=8, max_context=16)
    eng.warmup()
    batcher = ContinuousBatcher(eng)
    batcher.submit(Request(list(range(1, 30)), max_new_tokens=2))
    with pytest.raises(mx.MXNetError):
        batcher.run()


# ----------------------------------------------------------------------
# loadgen smoke (the tier-1 wiring of tools/serve_loadgen.py)
# ----------------------------------------------------------------------

@pytest.mark.slow   # CLI smoke; the serving_block schema itself is
# gated fast in test_bench_line.py
def test_serve_loadgen_smoke_cli():
    """`tools/serve_loadgen.py --smoke` runs end-to-end and prints one
    JSON line under the driver's tail-window budget."""
    import tools.serve_loadgen as slg
    payload = slg.run_loadgen(n_requests=6, max_batch=2, block_size=8,
                              max_context=32, mode="both", smoke=True)
    line = json.dumps({k: v for k, v in payload.items()
                       if k != "policies"})
    assert len(line) < 1800
    blk = payload["serving"]
    assert blk["compiles_after_warmup"] == 0
    assert blk["tokens_s"] is not None and blk["occupancy"] is not None
    assert payload["continuous_vs_static"]["tokens_per_step_ratio"] > 1.0


def test_sampler_accepts_compiled_step_function():
    """SequenceSampler/BeamSearchSampler drive a raw jax.jit step
    function (no NDArray wrapping, logits stay on device)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.gluon.model_zoo.nlp.sampler import (BeamSearchSampler,
                                                       SequenceSampler)
    vocab = 16

    @jax.jit
    def step(tok, states):
        # favour (tok + 1) % vocab; EOS=0 reachable from tok 15
        lp = jax.nn.log_softmax(
            10.0 * jax.nn.one_hot((tok + 1) % vocab, vocab), axis=-1)
        return lp, states
    beam = BeamSearchSampler(beam_size=2, decoder=step, eos_id=0,
                             max_length=20, sync_every=4)
    samples, scores, lengths = beam(mx.nd.array([14, 3]), {})
    s = samples.asnumpy()
    assert s.shape[:2] == (2, 2)
    assert s[0, 0, 1] == 15 and 0 in s[0, 0, 2:]     # 14 -> 15 -> EOS
    smp = SequenceSampler(beam_size=2, decoder=step, eos_id=0,
                          max_length=8, temperature=1.0, top_k=2)
    samples, scores, lengths = smp(mx.nd.array([5]), {})
    assert samples.shape[0] == 1 and samples.shape[1] == 2
