"""Cross-framework oracle for the fused RNN op (SURVEY §4
check_consistency technique): torch.nn.LSTM/GRU use the same cuDNN gate
order (i,f,g,o / r,z,n) and per-layer weight split as nd.RNN's packed
layout, so copying torch's weights into the packed vector must
reproduce torch's outputs and final states."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import mxnet_tpu as mx
from mxnet_tpu import nd


def _pack_from_torch(rnn, num_layers, bidirectional):
    """Flatten torch weights into nd.RNN's packed layout: all weights
    (layer-, then direction-major: W_ih, W_hh), then all biases."""
    dirs = 2 if bidirectional else 1
    chunks = []
    for part in ("weight", "bias"):
        for layer in range(num_layers):
            for d in range(dirs):
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                for kind in ("ih", "hh"):
                    w = getattr(rnn, f"{part}_{kind}{sfx}")
                    chunks.append(w.detach().numpy().ravel())
    return np.concatenate(chunks)


@pytest.mark.parametrize("mode,bidirectional,num_layers", [
    ("lstm", False, 1), ("lstm", True, 2), ("gru", False, 2),
])
def test_fused_rnn_matches_torch(mode, bidirectional, num_layers):
    T, B, I, H = 5, 3, 4, 6
    dirs = 2 if bidirectional else 1
    rng = np.random.RandomState(0)
    x = rng.randn(T, B, I).astype(np.float32)

    cls = torch.nn.LSTM if mode == "lstm" else torch.nn.GRU
    tr = cls(I, H, num_layers=num_layers, bidirectional=bidirectional)
    with torch.no_grad():
        t_out, t_state = tr(torch.from_numpy(x))
    packed = _pack_from_torch(tr, num_layers, bidirectional)

    h0 = nd.zeros((num_layers * dirs, B, H))
    kw = {"state_cell": nd.zeros((num_layers * dirs, B, H))} \
        if mode == "lstm" else {}
    res = nd.RNN(nd.array(x), nd.array(packed), h0, state_size=H,
                 num_layers=num_layers, mode=mode,
                 bidirectional=bidirectional, state_outputs=True, **kw)
    np.testing.assert_allclose(res[0].asnumpy(), t_out.numpy(),
                               rtol=1e-5, atol=1e-5)
    t_h = (t_state[0] if mode == "lstm" else t_state).numpy()
    np.testing.assert_allclose(res[1].asnumpy(), t_h, rtol=1e-5,
                               atol=1e-5)
    if mode == "lstm":
        np.testing.assert_allclose(res[2].asnumpy(),
                                   t_state[1].numpy(), rtol=1e-5,
                                   atol=1e-5)
