"""Round-3 op-tail semantics (reference: src/operator/{pad,lrn,
correlation,upsampling,crop}.cc, nn/group_norm.cc + the matching
tests/python/unittest/test_operator.py cases)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient, retry, with_seed)


def test_pad_modes():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(1, 1, 3, 4))
    pw = (0, 0, 0, 0, 1, 1, 2, 2)
    out = nd.Pad(x, mode="constant", pad_width=pw, constant_value=7.0)
    ref = np.pad(x.asnumpy(), ((0, 0), (0, 0), (1, 1), (2, 2)),
                 constant_values=7.0)
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-6)
    for mode in ("edge", "reflect"):
        out = nd.Pad(x, mode=mode, pad_width=pw)
        ref = np.pad(x.asnumpy(), ((0, 0), (0, 0), (1, 1), (2, 2)),
                     mode=mode)
        assert_almost_equal(out.asnumpy(), ref, rtol=1e-6)
    with pytest.raises(mx.MXNetError):
        nd.Pad(x, pad_width=(1, 1))


def test_argmax_channel():
    x = nd.array(np.random.RandomState(0).randn(2, 5, 3).astype(np.float32))
    out = nd.argmax_channel(x)
    np.testing.assert_array_equal(out.asnumpy(),
                                  x.asnumpy().argmax(axis=1))


@with_seed()
@retry(3)
def test_group_norm_matches_torch_and_grads():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(1)
    x = rng.randn(2, 6, 4, 4).astype(np.float32)
    g = rng.rand(6).astype(np.float32) + 0.5
    b = rng.randn(6).astype(np.float32)
    out = nd.GroupNorm(nd.array(x), nd.array(g), nd.array(b), num_groups=3)
    tout = torch.nn.functional.group_norm(
        torch.tensor(x), 3, torch.tensor(g), torch.tensor(b))
    assert_almost_equal(out.asnumpy(), tout.numpy(), rtol=1e-4, atol=1e-5)
    w = nd.array(rng.rand(2, 6, 4, 4).astype(np.float32))
    check_numeric_gradient(
        lambda v: (nd.GroupNorm(v, nd.array(g), nd.array(b),
                                num_groups=3) * w).sum(),
        [nd.array(x)], rtol=5e-2, atol=1e-2)


def test_lrn_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.abs(np.random.RandomState(2).randn(2, 8, 5, 5)).astype(np.float32)
    out = nd.LRN(nd.array(x), alpha=1e-3, beta=0.75, knorm=2.0, nsize=5)
    tout = torch.nn.functional.local_response_norm(
        torch.tensor(x), size=5, alpha=1e-3, beta=0.75, k=2.0)
    assert_almost_equal(out.asnumpy(), tout.numpy(), rtol=1e-4, atol=1e-5)


def test_upsampling_nearest_and_bilinear():
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    out = nd.UpSampling(x, scale=2, sample_type="nearest")
    assert out.shape == (1, 1, 8, 8)
    np.testing.assert_array_equal(out.asnumpy()[0, 0, :2, :2],
                                  np.zeros((2, 2)))
    np.testing.assert_array_equal(out.asnumpy()[0, 0, 6:, 6:],
                                  np.full((2, 2), 15.0))
    out = nd.UpSampling(x, scale=2, sample_type="bilinear")
    assert out.shape == (1, 1, 8, 8)
    assert np.isfinite(out.asnumpy()).all()


def test_crop_to_reference_and_center():
    x = nd.array(np.arange(36, dtype=np.float32).reshape(1, 1, 6, 6))
    like = nd.zeros((1, 1, 4, 4))
    out = nd.Crop(x, like, num_args=2, center_crop=True)
    np.testing.assert_array_equal(out.asnumpy(),
                                  x.asnumpy()[:, :, 1:5, 1:5])
    out = nd.Crop(x, offset=(2, 1), h_w=(3, 3))
    np.testing.assert_array_equal(out.asnumpy(),
                                  x.asnumpy()[:, :, 2:5, 1:4])


def test_correlation_identity_peak():
    """correlating a map with itself peaks at zero displacement."""
    rng = np.random.RandomState(3)
    x = rng.randn(1, 4, 6, 6).astype(np.float32)
    out = nd.Correlation(nd.array(x), nd.array(x), max_displacement=2,
                         pad_size=2)
    o = out.asnumpy()
    assert o.shape == (1, 25, 6, 6)
    center = o[0, 12]                     # (dy,dx)=(0,0) channel
    # zero-displacement of a self-correlation is the channel-mean of
    # squares exactly
    assert_almost_equal(center, (x ** 2).mean(axis=1)[0], rtol=1e-5)
    # displaced channels see zero-padded borders: the corner at max
    # negative displacement correlates with padding only
    np.testing.assert_allclose(o[0, 0, 0, 0], 0.0, atol=1e-6)


def test_correlation_gradient_flows():
    rng = np.random.RandomState(4)
    a = nd.array(rng.randn(1, 2, 5, 5).astype(np.float32))
    b = nd.array(rng.randn(1, 2, 5, 5).astype(np.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        loss = nd.Correlation(a, b, max_displacement=1, pad_size=1).sum()
    loss.backward()
    assert np.abs(a.grad.asnumpy()).sum() > 0
    assert np.abs(b.grad.asnumpy()).sum() > 0


def test_hard_sigmoid():
    x = nd.array(np.array([-5.0, -1.0, 0.0, 1.0, 5.0], dtype=np.float32))
    out = nd.hard_sigmoid(x)
    ref = np.clip(0.2 * x.asnumpy() + 0.5, 0.0, 1.0)
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-6)
    out2 = nd.hard_sigmoid(x, alpha=0.5, beta=0.25)
    assert_almost_equal(out2.asnumpy(),
                        np.clip(0.5 * x.asnumpy() + 0.25, 0.0, 1.0),
                        rtol=1e-6)
    # gradient: alpha inside the linear band, 0 where clipped
    x.attach_grad()
    with autograd.record():
        y = nd.hard_sigmoid(x)
    y.backward()
    assert_almost_equal(x.grad.asnumpy(),
                        np.array([0.0, 0.2, 0.2, 0.2, 0.0], np.float32),
                        rtol=1e-6)


@with_seed()
def test_digamma():
    x = nd.array(np.array([0.5, 1.0, 2.0, 5.0], dtype=np.float32))
    out = nd.digamma(x)
    # psi(1) = -euler_gamma; psi(2) = 1 - euler_gamma
    eg = 0.5772156649
    assert_almost_equal(out.asnumpy()[1], -eg, rtol=1e-5)
    assert_almost_equal(out.asnumpy()[2], 1.0 - eg, rtol=1e-5)
    check_numeric_gradient(lambda a: nd.digamma(a).sum(), [x], rtol=1e-2,
                           atol=1e-3)


@with_seed()
def test_shuffle_first_axis():
    x = nd.array(np.arange(24, dtype=np.float32).reshape(8, 3))
    out = nd.shuffle(x)
    # rows are permuted intact: same multiset of rows, same row contents
    got = out.asnumpy()
    assert sorted(got[:, 0].tolist()) == x.asnumpy()[:, 0].tolist()
    for row in got:
        base = row[0]
        np.testing.assert_allclose(row, [base, base + 1, base + 2])
