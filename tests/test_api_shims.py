"""API-parity modules: mx.name, mx.attribute, mx.engine, mx.rtc,
FilterSampler, MXTPU_EAGER debug switch (reference python/mxnet/{name,
attribute,engine,rtc}.py, gluon/data/sampler.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import data as gdata
from mxnet_tpu.gluon import nn


def test_name_manager_and_prefix():
    with mx.name.NameManager():
        a = mx.sym.relu(mx.sym.var("x"))
        b = mx.sym.relu(mx.sym.var("y"))
    assert a.name == "relu0" and b.name == "relu1"
    with mx.name.Prefix("mynet_"):
        s = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3)
    assert s.name.startswith("mynet_fullyconnected")
    # explicit names always win
    with mx.name.Prefix("p_"):
        t = mx.sym.relu(mx.sym.var("z"), name="myrelu")
    assert t.name == "myrelu"
    assert mx.name.current() is None


def test_attribute_scope_path():
    with mx.attribute.AttrScope(ctx_group="stage1"):
        v = mx.sym.var("w")
    assert v.attr("ctx_group") == "stage1"


def test_engine_shims():
    prev = mx.engine.set_bulk_size(8)
    assert mx.engine.set_bulk_size(prev) == 8
    with mx.engine.bulk(4):
        pass


def test_rtc_gated():
    with pytest.raises(mx.MXNetError):
        mx.rtc.CudaModule("__global__ void k() {}")
    with pytest.raises(mx.MXNetError):
        mx.rtc.CudaKernel()


def test_eager_debug_switch():
    os.environ["MXTPU_EAGER"] = "1"
    try:
        d = nn.Dense(2)
        d.initialize()
        d.hybridize()
        assert d._active is False        # NaiveEngine-equivalent: stays eager
        out = d(mx.nd.ones((1, 3)))
        assert out.shape == (1, 2)
    finally:
        del os.environ["MXTPU_EAGER"]
    d2 = nn.Dense(2)
    d2.initialize()
    d2.hybridize()
    assert d2._active is True


def test_filter_sampler():
    ds = gdata.ArrayDataset(mx.nd.array([1.0, 2.0, 3.0, 4.0]))
    fs = gdata.FilterSampler(lambda x: float(x) > 2, ds)
    assert list(fs) == [2, 3]
    assert len(fs) == 2
    loader = gdata.DataLoader(ds, batch_size=2, sampler=fs)
    (batch,) = list(loader)
    assert batch.asnumpy().tolist() == [3.0, 4.0]
