"""API-parity modules: mx.name, mx.attribute, mx.engine, mx.rtc,
FilterSampler, MXTPU_EAGER debug switch (reference python/mxnet/{name,
attribute,engine,rtc}.py, gluon/data/sampler.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import data as gdata
from mxnet_tpu.gluon import nn


def test_name_manager_and_prefix():
    with mx.name.NameManager():
        a = mx.sym.relu(mx.sym.var("x"))
        b = mx.sym.relu(mx.sym.var("y"))
    assert a.name == "relu0" and b.name == "relu1"
    with mx.name.Prefix("mynet_"):
        s = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=3)
    assert s.name.startswith("mynet_fullyconnected")
    # explicit names always win
    with mx.name.Prefix("p_"):
        t = mx.sym.relu(mx.sym.var("z"), name="myrelu")
    assert t.name == "myrelu"
    assert mx.name.current() is None


def test_attribute_scope_path():
    with mx.attribute.AttrScope(ctx_group="stage1"):
        v = mx.sym.var("w")
    assert v.attr("ctx_group") == "stage1"


def test_engine_shims():
    prev = mx.engine.set_bulk_size(8)
    assert mx.engine.set_bulk_size(prev) == 8
    with mx.engine.bulk(4):
        pass


def test_rtc_gated():
    with pytest.raises(mx.MXNetError):
        mx.rtc.CudaModule("__global__ void k() {}")
    with pytest.raises(mx.MXNetError):
        mx.rtc.CudaKernel()


def test_eager_debug_switch():
    os.environ["MXTPU_EAGER"] = "1"
    try:
        d = nn.Dense(2)
        d.initialize()
        d.hybridize()
        assert d._active is False        # NaiveEngine-equivalent: stays eager
        out = d(mx.nd.ones((1, 3)))
        assert out.shape == (1, 2)
    finally:
        del os.environ["MXTPU_EAGER"]
    d2 = nn.Dense(2)
    d2.initialize()
    d2.hybridize()
    assert d2._active is True


def test_filter_sampler():
    ds = gdata.ArrayDataset(mx.nd.array([1.0, 2.0, 3.0, 4.0]))
    fs = gdata.FilterSampler(lambda x: float(x) > 2, ds)
    assert list(fs) == [2, 3]
    assert len(fs) == 2
    loader = gdata.DataLoader(ds, batch_size=2, sampler=fs)
    (batch,) = list(loader)
    assert batch.asnumpy().tolist() == [3.0, 4.0]


def test_legacy_rnn_namespace():
    """mx.rnn (reference python/mxnet/rnn/): cells re-exported, bucketed
    sentence iterator feeds BucketingModule-style batches."""
    import numpy as np
    assert mx.rnn.LSTMCell is mx.gluon.rnn.LSTMCell
    rng = np.random.RandomState(0)
    sentences = [list(rng.randint(1, 50, size=n))
                 for n in rng.randint(3, 12, size=60)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=4,
                                   buckets=[5, 10, 15])
    assert it.default_bucket_key == 15
    seen = 0
    for batch in it:
        seen += 1
        assert batch.bucket_key in (5, 10, 15)
        assert batch.data[0].shape == (4, batch.bucket_key)
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        # label is data shifted one step left
        np.testing.assert_array_equal(l[:, :-1], d[:, 1:])
    assert seen > 0
    it.reset()
    assert sum(1 for _ in it) == seen

    # cell checkpoint helpers roundtrip through the shared container
    import tempfile, os
    cell = mx.rnn.LSTMCell(8)
    cell.initialize()
    x = mx.nd.ones((2, 4))
    states = cell.begin_state(batch_size=2)
    cell(x, states)
    prefix = os.path.join(tempfile.mkdtemp(), "rnnckpt")
    mx.rnn.save_rnn_checkpoint(cell, prefix, 3,
                               aux_params={"extra": mx.nd.array([7.0])})
    cell2 = mx.rnn.LSTMCell(8, prefix=cell.prefix)
    cell2.initialize()
    cell2(x, cell2.begin_state(batch_size=2))
    sym, args, aux = mx.rnn.load_rnn_checkpoint(cell2, prefix, 3)
    assert aux["extra"].asnumpy()[0] == 7.0   # aux survives the roundtrip
    for name, p in cell.collect_params().items():
        np.testing.assert_array_equal(
            cell2.collect_params()[name].data().asnumpy(),
            p.data().asnumpy())

    # time-major layout (the reference bucketing example uses 'TN')
    it_tn = mx.rnn.BucketSentenceIter(sentences, batch_size=4,
                                      buckets=[5, 10, 15], layout="TN")
    b = next(iter(it_tn))
    assert b.data[0].shape == (b.bucket_key, 4)
    assert it_tn.provide_data[0].shape == (15, 4)


def test_monitor_collects_weight_and_grad_stats():
    """mx.monitor.Monitor (reference python/mxnet/monitor.py) over the
    Module executor boundary."""
    import numpy as np
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    out = mx.sym.SoftmaxOutput(out, name="softmax")
    mod = mx.mod.Module(out, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, 5))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    mod.init_optimizer(kvstore=None)
    mon = mx.monitor.Monitor(interval=2, pattern=".*fc.*")
    mod.install_monitor(mon)
    batch = mx.io.DataBatch(data=[mx.nd.ones((2, 5))],
                            label=[mx.nd.array([0.0, 1.0])])
    stats_per_step = []
    for _ in range(4):
        mon.tic()
        mod.forward_backward(batch)
        mod.update()
        stats_per_step.append(mon.toc())
    # armed on steps 0 and 2 only (interval=2)
    assert len(stats_per_step[0]) > 0 and len(stats_per_step[2]) > 0
    assert stats_per_step[1] == [] and stats_per_step[3] == []
    names = {n for _, n, _ in stats_per_step[0]}
    assert any(n.endswith("_grad") for n in names), names
    assert any(not n.endswith("_grad") for n in names), names
    for _, _, stat in stats_per_step[0]:
        assert np.isfinite(stat)


def test_monitor_on_bucketing_module():
    """Monitor must reach the CURRENT bucket's executor (review finding:
    BucketingModule has no _exec of its own)."""
    def sym_gen(seq_len):
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc_shared",
                                   flatten=False)
        pooled = mx.sym.mean(fc, axis=1, name="pool")
        out = mx.sym.SoftmaxOutput(pooled, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16)
    mod.bind(data_shapes=[("data", (2, 16, 6))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    mon = mx.monitor.Monitor(interval=1, pattern=".*fc.*")
    mod.install_monitor(mon)
    mon.tic()
    mod.forward(mx.io.DataBatch(data=[mx.nd.ones((2, 16, 6))],
                                label=[mx.nd.zeros((2,))], bucket_key=16),
                is_train=False)
    stats = mon.toc()
    assert stats and all(len(t) == 3 for t in stats)


def test_legacy_model_namespace_and_module_checkpoint(tmp_path):
    """mx.model.save/load_checkpoint + callback.module_checkpoint
    (reference python/mxnet/model.py, callback.py)."""
    import numpy as np
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    out = mx.sym.SoftmaxOutput(out, name="softmax")
    mod = mx.mod.Module(out, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, 5))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    args, aux = mod.get_params()
    prefix = str(tmp_path / "legacy")
    mx.model.save_checkpoint(prefix, 2, out, args, aux)
    sym2, args2, aux2 = mx.model.load_checkpoint(prefix, 2)
    assert sym2 is not None
    for k in args:
        np.testing.assert_array_equal(args2[k].asnumpy(),
                                      args[k].asnumpy())
    with pytest.raises(mx.MXNetError, match="Module"):
        mx.model.FeedForward(out)

    cb = mx.callback.module_checkpoint(mod, str(tmp_path / "cbck"),
                                       period=2)
    cb(0)          # epoch 1: not a period boundary
    cb(1)          # epoch 2: checkpoint
    import os
    assert not os.path.exists(str(tmp_path / "cbck-0001.params"))
    assert os.path.exists(str(tmp_path / "cbck-0002.params"))


def test_module_optimizer_states_roundtrip(tmp_path):
    """save_checkpoint(save_optimizer_states=True) writes a .states file
    that load_optimizer_states restores exactly (review finding: the
    flag used to be silently ignored)."""
    import numpy as np
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    out = mx.sym.SoftmaxOutput(out, name="softmax")

    def make():
        m = mx.mod.Module(out, data_names=("data",),
                          label_names=("softmax_label",))
        m.bind(data_shapes=[("data", (2, 5))],
               label_shapes=[("softmax_label", (2,))])
        m.init_params()
        m.init_optimizer(kvstore=None, optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9})
        return m

    mod = make()
    batch = mx.io.DataBatch(data=[mx.nd.ones((2, 5))],
                            label=[mx.nd.array([0.0, 1.0])])
    for _ in range(3):
        mod.forward_backward(batch)
        mod.update()
    prefix = str(tmp_path / "st")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
    import os
    assert os.path.exists(prefix + "-0001.states")

    mod2 = make()
    mod2.set_params(*mod.get_params())
    mod2.load_optimizer_states(prefix + "-0001.states")
    for idx, st in mod._updater_states.items():
        comps = st if isinstance(st, (list, tuple)) else [st]
        comps2 = mod2._updater_states[idx]
        comps2 = comps2 if isinstance(comps2, (list, tuple)) else [comps2]
        for a, b in zip(comps, comps2):
            if a is not None:
                np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
    # the restored momentum produces the identical next step
    mod.forward_backward(batch); mod.update()
    mod2.forward_backward(batch); mod2.update()
    for (k, a), (_, b) in zip(sorted(mod.get_params()[0].items()),
                              sorted(mod2.get_params()[0].items())):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(), rtol=1e-6)


def test_bucket_iter_int64_ids_and_discard_warning(caplog):
    import logging
    import numpy as np
    big = 2 ** 24 + 3      # would round in a float32 staging buffer
    sentences = [[big, 1, 2], [3, 4, 5], list(range(40))]
    with caplog.at_level(logging.WARNING):
        it = mx.rnn.BucketSentenceIter(sentences, batch_size=2,
                                       buckets=[4], dtype="int64")
    assert "discarded 1" in caplog.text
    b = next(iter(it))
    # int64 narrows to int32 without MXTPU_INT64 (documented large-tensor
    # mode); the id VALUE must survive — a float32 staging buffer would
    # have rounded 2^24+3 to 2^24+4
    assert b.data[0].dtype in (np.int32, np.int64)
    assert big in b.data[0].asnumpy()


def test_module_optimizer_states_via_kvstore(tmp_path):
    """The DEFAULT init_optimizer path (kvstore='local',
    update_on_kvstore) keeps state in the store's updater — the .states
    file must carry THAT state (review finding: it silently wrote an
    empty file)."""
    import numpy as np
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    out = mx.sym.SoftmaxOutput(out, name="softmax")

    def make(params=None):
        m = mx.mod.Module(out, data_names=("data",),
                          label_names=("softmax_label",))
        m.bind(data_shapes=[("data", (2, 5))],
               label_shapes=[("softmax_label", (2,))])
        m.init_params()
        if params is not None:
            # with update_on_kvstore the STORE snapshots weights at
            # init_optimizer, so params must be set before it (the
            # reference resume flow orders it the same way)
            m.set_params(*params)
        m.init_optimizer(optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9})
        return m

    mod = make()
    assert mod._update_on_kvstore
    batch = mx.io.DataBatch(data=[mx.nd.ones((2, 5))],
                            label=[mx.nd.array([0.0, 1.0])])
    for _ in range(3):
        mod.forward_backward(batch)
        mod.update()
    prefix = str(tmp_path / "kvst")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
    import os
    assert os.path.getsize(prefix + "-0001.states") > 0

    mod2 = make(params=mod.get_params())
    mod2.load_optimizer_states(prefix + "-0001.states")
    mod.forward_backward(batch); mod.update()
    mod2.forward_backward(batch); mod2.update()
    for (k, a), (_, b) in zip(sorted(mod.get_params()[0].items()),
                              sorted(mod2.get_params()[0].items())):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(), rtol=1e-6,
                                   err_msg=k)


def test_util_np_flags_linked():
    """set_np/set_np_shape keep linked flags like the reference (array
    semantics require shape semantics)."""
    u = mx.util
    u.reset_np()
    assert not u.is_np_shape() and not u.is_np_array()
    with pytest.raises(ValueError):
        u.set_np(shape=False, array=True)
    u.set_np()
    assert u.is_np_shape() and u.is_np_array()
    u.reset_np()

    @u.use_np
    def f():
        return u.is_np_shape(), u.is_np_array()

    assert f() == (True, True)
    assert (u.is_np_shape(), u.is_np_array()) == (False, False)
    assert u.use_np_array is u.use_np


def test_test_utils_download_and_list_gpus(tmp_path):
    assert mx.test_utils.list_gpus() == []
    p = tmp_path / "blob.bin"
    p.write_bytes(b"x")
    assert mx.test_utils.download("http://host/blob.bin",
                                  fname=str(p)) == str(p)
    assert mx.test_utils.download("http://host/blob.bin", fname=str(p),
                                  overwrite=True) == str(p)
    with pytest.raises(mx.MXNetError):
        mx.test_utils.download("http://host/missing.bin",
                               fname=str(tmp_path / "missing.bin"))
