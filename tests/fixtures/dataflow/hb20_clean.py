# HB20 near-misses — every function here is CLEAN:
#   distinct arrays in distinct donated positions, duplicates into a
#   NON-donating call, aliases of non-donated arguments, and a closure
#   over the REBOUND result rather than the donor.
import jax


def distinct_args(params, opt_state, batch):
    step = jax.jit(lambda p, s, b: (p, s), donate_argnums=(0, 1))
    params, opt_state = step(params, opt_state, batch)
    return params


def duplicate_into_plain_call(params, batch):
    plain = jax.jit(lambda p, q, b: p)  # no donation: aliasing is fine
    return plain(params, params, batch)


def alias_of_non_donated(params, batch):
    step = jax.jit(lambda p, b: p, donate_argnums=(0,))
    keep = lambda: batch.sum()  # noqa: E731 — batch is not donated
    params = step(params, batch)
    return params, keep


class Holder:
    def stash_result(self, params, batch):
        step = jax.jit(lambda p, b: p, donate_argnums=(0,))
        params = step(params, batch)
        self._snapshot = params  # alias of the FRESH buffer: fine
        return params
