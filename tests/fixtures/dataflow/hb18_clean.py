# HB18 near-misses — every function here is CLEAN:
#   rebinding from the dispatch result, explicit donation opt-out,
#   non-donated positions read freely, and the loop that rebinds its
#   carry each iteration (the healthy trainer shape).
import jax


def rebinds(params, opt_state, batch):
    step = jax.jit(lambda p, s, b: (p, s), donate_argnums=(0, 1))
    params, opt_state = step(params, opt_state, batch)
    return params  # fresh binding from the result: fine


def opted_out(params, batch):
    step = jax.jit(lambda p, b: p, donate_argnums=())
    out = step(params, batch)
    return params  # nothing was donated


def non_donated_position(params, batch):
    step = jax.jit(lambda p, b: p, donate_argnums=(0,))
    out = step(params, batch)
    return batch  # position 1 is not donated


def carry_loop(params, batches):
    step = jax.jit(lambda p, b: p, donate_argnums=(0,))
    for b in batches:
        params = step(params, b)  # rebound every iteration
    return params
