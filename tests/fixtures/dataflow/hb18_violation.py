# HB18 fixture — use-after-donate, three planted bugs (line order):
#   1. read of a name after it was donated to a locally-jitted call
#   2. dispatch-through: helper(jitted, params, ...) donates position 0
#      of the *inner* callable; the stale name is returned
#   3. loop wraparound: donation in iteration N poisons the read at the
#      top of iteration N+1 even though the read precedes it textually
import jax


def plain_step(params, opt_state, batch):
    step = jax.jit(lambda p, s, b: (p, s), donate_argnums=(0, 1))
    new_p, new_s = step(params, opt_state, batch)
    return params  # BUG: donated at the call above; use new_p


def _dispatch(fn, *args):
    return fn(*args)


def dispatched_step(params, batch):
    jitted = jax.jit(lambda p, b: p, donate_argnums=(0,))
    out = _dispatch(jitted, params, batch)
    stale = params  # BUG: donated through the dispatch helper
    return out, stale


def wraparound(params, batches):
    step = jax.jit(lambda p, b: p, donate_argnums=(0,))
    for b in batches:
        norm = params.sum()  # BUG on iteration 2: donated last round
        step(params, b)
    return norm
