# HB20 fixture — donation aliasing, three planted bugs (line order):
#   1. the same array passed twice into one donated call (XLA donates
#      the buffer once; the second reference dangles)
#   2. donated arg previously stored into a self-field that outlives
#      the call
#   3. donated arg captured by a closure defined before the call
import jax


def duplicate_positions(params, batch):
    step = jax.jit(lambda p, q, b: p, donate_argnums=(0,))
    return step(params, params, batch)  # BUG: params donated AND read


class Holder:
    def stash_then_donate(self, params, batch):
        step = jax.jit(lambda p, b: p, donate_argnums=(0,))
        self._snapshot = params  # alias outlives the donating call
        return step(params, batch)  # BUG: self._snapshot dangles


def closure_capture(params, batch):
    step = jax.jit(lambda p, b: p, donate_argnums=(0,))
    metrics = lambda: params.sum()  # noqa: E731 — captures params
    out = step(params, batch)  # BUG: metrics() reads a dead buffer
    return out, metrics
