# HB19 fixture — mesh-axis consistency, three planted bugs (line order):
#   1. non-canonical string axis in a PartitionSpec
#   2. unknown AXIS_* constant in a collective (no MeshConfig can
#      construct it)
#   3. canonical axis used by a collective OUTSIDE the axes the
#      enclosing scope's MeshConfig declares
import jax
from jax.sharding import PartitionSpec as P
from jax import lax

from mxnet_tpu.parallel.mesh import AXIS_DP, AXIS_TP, MeshConfig

AXIS_SP = "sp"  # a local invention — NOT in the canonical catalog


def bad_spec_string(x):
    return P("sp", None)  # BUG: "sp" is not a canonical axis


def bad_collective_const(x):
    return lax.psum(x, AXIS_SP)  # BUG: AXIS_SP is not canonical


def collective_off_mesh(x):
    cfg = MeshConfig(dp=8)
    y = lax.psum(x, AXIS_DP)  # fine: dp is declared
    return lax.pmean(y, axis_name=AXIS_TP)  # BUG: no tp axis on cfg
