# HB19 near-misses — every function here is CLEAN:
#   canonical AXIS_* constants inside the declared mesh, spec usage
#   (no scope gating on P(...)), scopes with no/ambiguous MeshConfig
#   declarations, and size-1 axes deliberately excluded from the
#   declaration set only when a collective never touches them.
import jax
from jax.sharding import PartitionSpec as P
from jax import lax
from jax.experimental.shard_map import shard_map

from mxnet_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_TP, MeshConfig


def declared_and_used(x):
    cfg = MeshConfig(dp=8, tp=2)
    y = lax.psum(x, AXIS_DP)
    return lax.pmean(y, axis_name=AXIS_TP)


def spec_only_no_gating(x):
    # P(...) placements are legal for axes the mesh merely *has*; only
    # collectives are gated against the declared scope
    cfg = MeshConfig(dp=8)
    return P(AXIS_TP, None)


def no_declaration_scope(x):
    # nothing declared here: scope gating is off, canonical is enough
    return lax.psum(x, AXIS_PP)


def ambiguous_declarations(x, big):
    # two MeshConfigs in one scope: ambiguous, gating is off
    a = MeshConfig(dp=8)
    b = MeshConfig(dp=4, tp=2)
    cfg = b if big else a
    return lax.psum(x, AXIS_TP)


def shard_mapped(fn, mesh, x):
    cfg = MeshConfig(dp=8, tp=2)
    mapped = shard_map(fn, mesh=mesh,
                       in_specs=P(AXIS_DP, AXIS_TP),
                       out_specs=P(AXIS_DP))
    return mapped(x)
