# HB21 fixture — unscaled low-precision casts, four planted bugs
# (line order):
#   1. raw astype to int8 (no amax scale anywhere near the cast)
#   2. raw astype to fp8-e4m3 codes
#   3. string-dtype form of the same bug
#   4. lax.convert_element_type to bf16 mid-graph
import jax.numpy as jnp
from jax import lax


def pack_grads(g):
    return g.astype(jnp.int8)  # BUG: |g| > 127 saturates silently


def cache_keys(k):
    return k.astype(jnp.float8_e4m3fn)  # BUG: tails flushed at 448


def wire_codes(x):
    return x.astype("int8")  # BUG: string-dtype form, same clip


def narrow_activations(x):
    return lax.convert_element_type(x, jnp.bfloat16)  # BUG: raw cast
