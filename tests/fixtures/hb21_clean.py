# HB21 fixture — near-misses that must NOT fire:
#   - casts to wide dtypes (f32/i32/u8)
#   - dtype names in non-cast positions (zeros/full construction)
#   - the scaled-helper route itself
#   - a justified per-line suppression
import jax.numpy as jnp
from jax import lax

from mxnet_tpu.ops.quant_matmul import quantize_rtn_int8


def widen(x):
    return x.astype(jnp.float32)          # widening: no clip risk


def counters(x):
    return x.astype(jnp.int32)            # wide int: fine


def fresh_pool(n):
    # CONSTRUCTION at a narrow dtype is not a cast of live values
    return jnp.zeros((n, 4), dtype=jnp.int8)


def scaled(x, scale):
    return quantize_rtn_int8(x, scale)    # the sanctioned route


def wire(x):
    # bf16 keeps f32's exponent range — scale-free by design here
    y = x.astype(jnp.bfloat16)  # mxlint: disable=HB21 -- comms wire
    return lax.psum(y, "i")
