"""HB15 clean near-misses: the same two locks taken SEQUENTIALLY (no
nesting) and nested in ONE consistent global order everywhere."""
import threading

table_lock = threading.Lock()
index_lock = threading.Lock()

_table = {}
_index = {}


def update(key, value):
    with table_lock:                 # consistent order: table -> index
        _table[key] = value
        with index_lock:
            _index[key] = len(_table)


def reindex():
    with table_lock:                 # SAME order: table -> index
        keys = list(_table)
        with index_lock:
            for k in keys:
                _index[k] = 0


def snapshot():
    with table_lock:                 # sequential, never nested: no edge
        t = dict(_table)
    with index_lock:
        i = dict(_index)
    return t, i
