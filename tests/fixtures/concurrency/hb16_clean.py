"""HB16 clean near-misses: the blocking work happens OUTSIDE the
critical section (snapshot-then-act); `cv.wait()` on the HELD condition
is the supported idiom; dict `.get` under a lock is not a queue wait."""
import time
import threading

state_lock = threading.Lock()
_cache = {}


class Worker:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._sock = sock
        self._pending = []

    def flush(self, payload):
        with self._lock:
            out = list(self._pending)   # snapshot under the lock
            self._pending.clear()
        for p in out:
            self._sock.sendall(p)       # blocking work after release

    def wait_for_work(self):
        with self._cv:
            while not self._pending:
                self._cv.wait(timeout=1)   # held condition: the idiom
            return self._pending.pop()

    def lookup(self, key):
        with self._lock:
            return _cache.get(key)      # dict.get: not a queue wait


def backoff():
    time.sleep(0.01)                    # sleep with no lock held: fine
