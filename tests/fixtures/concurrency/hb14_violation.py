"""HB14 seeded violation: a stats class whose worker thread writes a
counter under the lock while the reporter reads it bare — the planted
bug the unguarded-shared-state pass must catch."""
import threading


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.processed = 0
        self.errors = 0

    def add(self, failed=False):
        with self._lock:
            self.processed += 1
            if failed:
                self.errors += 1

    def summary(self):
        # SEEDED HB14: bare read races the worker's locked writes
        return {"processed": self.processed, "errors": self.errors}

    def start(self, work):
        t = threading.Thread(target=lambda: [self.add(w) for w in work])
        t.start()
        return t


class Annotated:
    """Guarded-by annotation path: no lock usage anywhere, the
    declaration alone makes the bare write a violation."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table = {}   # guarded-by: _lock

    def poke(self, k, v):
        self._table[k] = v          # SEEDED HB14: declared guarded
