"""HB16 seeded violations: blocking operations inside `with lock:`
bodies — a sleep, a queue wait, file I/O, a jitted dispatch, and an RPC
reached through a module helper (one-level resolution)."""
import queue
import time
import threading

import jax

state_lock = threading.Lock()
work_queue = queue.Queue()


def _send(sock, payload):
    sock.sendall(payload)            # the blocking body of the helper


class Worker:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self._sock = sock
        self._log = None

    def poll(self):
        with self._lock:
            item = work_queue.get(timeout=1)   # SEEDED: queue wait
            time.sleep(0.01)                   # SEEDED: sleep
        return item

    def flush(self, payload):
        with self._lock:
            _send(self._sock, payload)         # SEEDED: RPC via helper

    def record(self, line):
        with self._lock:
            self._log = open("log.txt", "a")   # SEEDED: file I/O
            self._log.flush()                  # SEEDED: file I/O


def dispatch(step, x):
    f = jax.jit(step)
    with state_lock:
        y = f(x)                               # SEEDED: jitted dispatch
        y.block_until_ready()                  # SEEDED: device sync
    return y
