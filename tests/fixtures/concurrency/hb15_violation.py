"""HB15 seeded violation: two code paths nest the same two module locks
in OPPOSITE orders — the classic AB/BA deadlock, visible statically as
a cycle in the acquisition graph (one edge goes through a helper call,
exercising the one-level interprocedural resolution)."""
import threading

table_lock = threading.Lock()
index_lock = threading.Lock()

_table = {}
_index = {}


def update(key, value):
    with table_lock:                 # order: table -> index
        _table[key] = value
        with index_lock:
            _index[key] = len(_table)


def _drop(key):
    with table_lock:                 # acquired by reindex UNDER index
        _table.pop(key, None)


def reindex():
    with index_lock:                 # order: index -> table (SEEDED)
        for key in list(_index):
            _drop(key)
