"""HB14 clean near-misses: every shared-field access holds the lock;
init-only config fields read bare are immutable (exempt); a method
declared `# guarded-by:` is analyzed as running under the lock."""
import threading


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.processed = 0
        self.batch_size = 32        # written ONLY here: immutable config

    def add(self):
        with self._lock:
            self.processed += 1
            self._note()

    def _note(self):  # guarded-by: _lock
        self.processed += 0         # caller holds the lock: clean

    def summary(self):
        with self._lock:            # snapshot under the lock
            n = self.processed
        return {"processed": n, "batch": self.batch_size}

    def start(self, work):
        t = threading.Thread(target=lambda: [self.add() for _ in work])
        t.start()
        return t
