"""HB17 clean fixture: the same call sites routed through the
MeshConfig axis-name contract."""
from jax import lax
from jax.sharding import PartitionSpec as P

from mxnet_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_TP, MeshConfig


def batch_spec(ndim):
    spec = [None] * ndim
    spec[0] = AXIS_DP
    return P(*spec)


def collective(x, mesh):
    i = lax.axis_index(AXIS_TP)
    dp = mesh.shape[AXIS_DP]
    cfg = MeshConfig.for_mesh(mesh)
    return lax.psum(x, AXIS_PP) + i + dp + cfg.dp
