"""HB17 fixture: hardcoded mesh-axis literals (each marked line is a
seeded planted bug the lint regression test must keep catching)."""
from jax import lax
from jax.sharding import PartitionSpec as P


def batch_spec():
    return P("dp", None)                 # HB17: literal axis in P(...)


def collective(x, mesh):
    i = lax.axis_index("tp")             # HB17: literal axis name
    dp = mesh.shape["dp"]                # HB17: literal shape key
    first = mesh.shape[0]                # HB17: positional axis index
    return lax.psum(x, "pp") + i + dp + first   # HB17: literal axis
