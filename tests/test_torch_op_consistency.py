"""Systematic cross-framework consistency sweep (SURVEY §4
check_consistency): elementwise/reduction/linalg ops against torch on
shared inputs. Complements the per-op numeric-gradient checks with an
independent numerical oracle."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from mxnet_tpu import nd

RNG = np.random.RandomState(7)
POS = RNG.rand(3, 4).astype(np.float32) + 0.1       # (0.1, 1.1)
ANY = RNG.randn(3, 4).astype(np.float32)
UNIT = np.clip(RNG.randn(3, 4).astype(np.float32), -0.99, 0.99)
GE1 = POS + 1.0

UNARY = [
    ("exp", torch.exp, ANY), ("log", torch.log, POS),
    ("log2", torch.log2, POS), ("log10", torch.log10, POS),
    ("log1p", torch.log1p, POS), ("expm1", torch.expm1, ANY),
    ("sqrt", torch.sqrt, POS), ("rsqrt", torch.rsqrt, POS),
    ("cbrt", lambda t: torch.sign(t) * torch.abs(t) ** (1 / 3), POS),
    ("abs", torch.abs, ANY), ("sign", torch.sign, ANY),
    ("floor", torch.floor, ANY), ("ceil", torch.ceil, ANY),
    ("trunc", torch.trunc, ANY), ("rint", torch.round, ANY),
    ("sin", torch.sin, ANY), ("cos", torch.cos, ANY),
    ("tan", torch.tan, UNIT), ("arcsin", torch.asin, UNIT),
    ("arccos", torch.acos, UNIT), ("arctan", torch.atan, ANY),
    ("sinh", torch.sinh, ANY), ("cosh", torch.cosh, ANY),
    ("tanh", torch.tanh, ANY), ("arcsinh", torch.asinh, ANY),
    ("arccosh", torch.acosh, GE1), ("arctanh", torch.atanh, UNIT),
    ("sigmoid", torch.sigmoid, ANY), ("erf", torch.erf, ANY),
    ("erfinv", torch.erfinv, UNIT * 0.9),
    ("gamma", lambda t: torch.exp(torch.lgamma(t)), POS),
    ("gammaln", torch.lgamma, POS),
    ("relu", torch.relu, ANY),
    ("softsign", torch.nn.functional.softsign, ANY),
    ("reciprocal", torch.reciprocal, POS),
]


@pytest.mark.parametrize("name,tfn,data", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary_matches_torch(name, tfn, data):
    got = getattr(nd, name)(nd.array(data)).asnumpy()
    want = tfn(torch.from_numpy(data)).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


BINARY = [
    ("add", torch.add), ("subtract", torch.sub),
    ("multiply", torch.mul), ("divide", torch.div),
    ("power", torch.pow), ("maximum", torch.maximum),
    ("minimum", torch.minimum), ("hypot", torch.hypot),
    ("arctan2", torch.atan2), ("fmod", torch.fmod),
    ("mod", torch.fmod),       # reference mod IS C fmod (round-4 fix)
]


@pytest.mark.parametrize("name,tfn", BINARY, ids=[b[0] for b in BINARY])
def test_binary_matches_torch(name, tfn):
    # positive bases: a negative base with a fractional exponent NaNs in
    # both frameworks and equal_nan would make the comparison vacuous
    a, b = (POS if name == "power" else ANY), POS + 0.5
    got = getattr(nd, name)(nd.array(a), nd.array(b)).asnumpy()
    want = tfn(torch.from_numpy(a), torch.from_numpy(b)).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


REDUCE = [
    ("sum", torch.sum), ("mean", torch.mean), ("prod", torch.prod),
    ("max", torch.amax), ("min", torch.amin),
]


@pytest.mark.parametrize("name,tfn", REDUCE, ids=[r[0] for r in REDUCE])
def test_reductions_match_torch(name, tfn):
    x = ANY
    got = getattr(nd, name)(nd.array(x), axis=1).asnumpy()
    want = tfn(torch.from_numpy(x), dim=1).numpy()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)
    got_all = getattr(nd, name)(nd.array(x)).asnumpy()
    want_all = tfn(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(np.ravel(got_all), np.ravel(want_all),
                               rtol=2e-5, atol=2e-6)


def test_softmax_families_match_torch():
    x = torch.from_numpy(ANY)
    np.testing.assert_allclose(
        nd.softmax(nd.array(ANY), axis=-1).asnumpy(),
        torch.softmax(x, dim=-1).numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        nd.log_softmax(nd.array(ANY), axis=-1).asnumpy(),
        torch.log_softmax(x, dim=-1).numpy(), rtol=1e-5, atol=1e-6)


def test_linalg_matches_torch():
    a = RNG.randn(4, 4).astype(np.float32)
    spd = (a @ a.T + 4 * np.eye(4)).astype(np.float32)
    np.testing.assert_allclose(
        nd.linalg.potrf(nd.array(spd)).asnumpy(),
        torch.linalg.cholesky(torch.from_numpy(spd)).numpy(),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.ravel(nd.linalg.det(nd.array(spd)).asnumpy()),
        np.ravel(torch.linalg.det(torch.from_numpy(spd)).numpy()),
        rtol=1e-4)
    np.testing.assert_allclose(
        nd.linalg.inverse(nd.array(spd)).asnumpy(),
        torch.linalg.inv(torch.from_numpy(spd)).numpy(),
        rtol=1e-3, atol=1e-4)


def test_conv_and_pool_match_torch():
    x = RNG.randn(2, 3, 8, 8).astype(np.float32)
    w = RNG.randn(5, 3, 3, 3).astype(np.float32)
    got = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                         num_filter=5, stride=(2, 2), pad=(1, 1),
                         no_bias=True).asnumpy()
    want = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(w), stride=2,
        padding=1).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    got = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="max").asnumpy()
    want = torch.nn.functional.max_pool2d(torch.from_numpy(x), 2).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_spatial_transformer_family_matches_torch():
    """GridGenerator+BilinearSampler (= SpatialTransformer) vs torch's
    affine_grid+grid_sample — the cuDNN convention both reference ops
    wrap is torch's align_corners=True."""
    x = RNG.randn(2, 3, 7, 9).astype(np.float32)
    theta = np.stack([
        np.array([[0.8, 0.1, 0.1], [-0.05, 0.9, -0.2]], np.float32),
        np.array([[1.1, 0.0, -0.3], [0.0, 0.7, 0.25]], np.float32)])
    got = nd.SpatialTransformer(nd.array(x),
                                nd.array(theta.reshape(2, 6)),
                                target_shape=(5, 6)).asnumpy()
    grid = torch.nn.functional.affine_grid(
        torch.from_numpy(theta), (2, 3, 5, 6), align_corners=True)
    want = torch.nn.functional.grid_sample(
        torch.from_numpy(x), grid, mode="bilinear",
        align_corners=True).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_contrib_fft_matches_torch():
    x = RNG.randn(3, 8).astype(np.float32)
    got = nd.contrib.fft(nd.array(x)).asnumpy()
    tc = torch.fft.fft(torch.from_numpy(x), dim=-1)
    want = np.stack([tc.real.numpy(), tc.imag.numpy()],
                    axis=-1).reshape(3, 16)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    back = nd.contrib.ifft(nd.array(got)).asnumpy()
    np.testing.assert_allclose(back / 8.0, x, rtol=1e-4, atol=1e-4)
