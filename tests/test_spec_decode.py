"""Speculative decoding through the compiled-step seam (ISSUE 17).

THE acceptance gates:

- greedy speculative output is BITWISE (fp32 argmax-exact) the
  non-speculative decode stream across K in {2, 3} here plus {4, 8} in
  the slow-marked twin (the W=8/W=16 verify compiles are single-core
  XLA time tier-1 cannot spare), including when per-sequence fallback
  kicks in mid-stream (always-missing drafts);
- identical work takes STRICTLY FEWER engine dispatches speculatively
  (deterministic CPU count, not a walltime claim);
- ``compiles_after_warmup`` stays 0 under speculative traffic (the
  verify family is warmup-compiled like every other graph);
- ``MXTPU_SPEC_DECODE`` unset/0 is a bitwise-inert kill switch (spec
  off = the plain engine: zero verify dispatches, same stream);
- the acceptance-rate gauge is published from real accounting;
- the PrefixCache draft-source trie walk (``continuation``) is
  refcount-NEUTRAL, respects partial tails, and degrades to "no draft"
  (never a crash) when chains are evicted mid-draft.

Every engine here shares ONE compile cache: the verify signatures
carry the width bucket, so the K=3 engine's {2,4} widths cover the
K=2 engine's buckets and the file pays each compile once (in the
``warm`` fixture's setup, outside any test's call budget).
"""
import os

import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError, NotSupportedError
from mxnet_tpu.gluon.model_zoo.nlp.llama import (LlamaConfig,
                                                 LlamaForCausalLM)
from mxnet_tpu.serving import (ContinuousBatcher, DraftSource,
                               InferenceEngine, PagedKVCache,
                               PrefixCache, Request)

nd = mx.nd

_VOCAB = 48
_CC = {}      # module-wide shared compile cache (one compile per graph)

# self-repeating prompts: the prompt-lookup n-gram source fires on the
# trailing gram, so speculative boundaries really draft
_PROMPTS = ((1, 2, 3, 1, 2, 3, 1),
            (5, 6, 7, 5, 6),
            (9, 10, 9, 10, 9, 10))
_MAX_NEW = 6


@pytest.fixture(scope="module")
def net():
    # one layer keeps the verify-family compiles inside the tier-1 time
    # budget; multi-layer speculative decode runs in the slow chaos
    # scenarios (2-layer nets, MXTPU_SPEC_DECODE=1 in tpu_queue_runner)
    cfg = LlamaConfig(vocab_size=_VOCAB, hidden_size=32, num_layers=1,
                      num_heads=4, num_kv_heads=2, intermediate_size=64,
                      max_seq_len=64, tie_embeddings=True)
    n = LlamaForCausalLM(cfg)
    n.initialize()
    n(nd.array([[1, 2, 3]], dtype="int32"))
    n.hybridize()
    return n


def _engine(net, **kw):
    # single context bucket (block_size == max_context): the
    # bucket-crossing machinery has its own gates in test_serving.py;
    # here one n_blocks keeps the verify family at 4 compiles total
    # (block-boundary speculation runs in the slow chaos scenario)
    kw.setdefault("max_batch", 3)
    kw.setdefault("block_size", 16)
    kw.setdefault("max_context", 16)
    eng = InferenceEngine(net, prefix_cache=False, compile_cache=_CC,
                          **kw)
    return eng.warmup()


@pytest.fixture(scope="module")
def warm(net):
    """Pay every compile ONCE, in fixture setup: the spec_k=3 warmup
    covers the verify widths {2,4} plus the base graphs, so each
    test's call phase stays inside the tier-1 duration budget.  Every
    wider verify graph is single-core XLA time the tier-1 clock cannot
    spare (W=8 ~6 s, W=16 ~12 s) — those compile in the slow-marked
    K∈{8,4} twin."""
    _engine(net, spec_decode=True, spec_k=3)


def _run(net, **kw):
    """The standard mix through a fresh engine + batcher; returns
    (engine, batcher, {prompt: generated})."""
    eng = _engine(net, **kw)
    b = ContinuousBatcher(eng)
    for p in _PROMPTS:
        b.submit(Request(list(p), max_new_tokens=_MAX_NEW))
    b.run()
    outs = {tuple(r.tokens): list(r.generated) for r in b.finished}
    assert len(outs) == len(_PROMPTS)
    return eng, b, outs


@pytest.fixture(scope="module")
def ref_run(net, warm):
    """The plain greedy stream under DEFAULT env — doubling as the
    kill-switch baseline: MXTPU_SPEC_DECODE unset means no verify
    graphs, no drafts, the pre-speculative engine."""
    os.environ.pop("MXTPU_SPEC_DECODE", None)
    eng, b, outs = _run(net)
    assert eng.spec_decode is False and b.speculative is False
    assert b.verify_steps == 0 and eng.stats["verify_calls"] == 0
    assert eng.cache.check_leaks()
    return eng.stats["decode_calls"], outs


# ----------------------------------------------------------------------
# the tentpole gate: bitwise-greedy parity across K, fewer dispatches,
# zero compiles after warmup
# ----------------------------------------------------------------------

def _assert_parity(net, ref_run, ks):
    from mxnet_tpu import telemetry
    plain_dispatches, ref = ref_run
    for k in ks:
        eng, b, outs = _run(net, spec_decode=True, spec_k=k)
        assert outs == ref, f"spec_k={k} diverged from plain greedy"
        assert eng.stats["compiles_after_warmup"] == 0
        assert b.verify_steps > 0 and eng.stats["verify_calls"] > 0
        assert eng.stats["draft_tokens_scored"] > 0
        st = b.stats()
        assert st["spec_accept_rate"] is not None
        assert st["tokens_per_dispatch"] is not None
        # strictly fewer dispatches for identical work: every verify
        # call replaces >= 1 plain decode, accepted drafts replace more
        spec_dispatches = (eng.stats["decode_calls"]
                          + eng.stats["verify_calls"])
        assert spec_dispatches < plain_dispatches, \
            f"spec_k={k}: {spec_dispatches} vs plain {plain_dispatches}"
        assert eng.cache.check_leaks()
        if telemetry.enabled():
            assert telemetry.value("serving.spec_accept_rate") \
                is not None


def test_speculative_bitwise_parity_across_k(net, ref_run):
    # the larger K first: its verify widths {2,4} superset K=2's in
    # the shared cache (one compile, via `warm`, pays for both);
    # spec_k=3 exercises multi-token drafts AND a non-power-of-two cap
    # bucketing into W=4
    _assert_parity(net, ref_run, (3, 2))


@pytest.mark.slow
def test_speculative_bitwise_parity_k4_k8(net, ref_run):
    # spec_k∈{4,8} add the W=8/W=16 verify graphs (~18 s of XLA on one
    # core) — same gate, budgeted outside tier-1 like the chaos
    # scenarios
    _assert_parity(net, ref_run, (8, 4))


def test_speculative_mid_stream_fallback_stays_bitwise(net, ref_run):
    """Drafts that always miss: acceptance collapses, the per-sequence
    cooldown disables drafting mid-stream, and the stream STAYS bitwise
    the plain one (fallback is a scheduling change, never an output
    change)."""
    _, ref = ref_run

    class _AlwaysWrong(DraftSource):
        def propose(self, context, k):
            if k <= 0:
                return []
            # one draft per boundary, guaranteed != the greedy argmax:
            # the reference stream says what comes after this exact
            # context, so propose something else
            key = tuple(context)
            for p, gen in ref.items():
                full = list(p) + gen
                for i in range(len(p), len(full)):
                    if tuple(full[:i]) == key:
                        return [(full[i] + 1) % _VOCAB]
            return []

    eng = _engine(net, spec_decode=True, spec_k=2)
    b = ContinuousBatcher(eng)
    b.draft = _AlwaysWrong()
    for p in _PROMPTS:
        b.submit(Request(list(p), max_new_tokens=_MAX_NEW))
    b.run()
    outs = {tuple(r.tokens): list(r.generated) for r in b.finished}
    assert outs == ref
    st = b.stats()
    # every draft missed...
    assert st["spec_accept_rate"] == 0.0 and b.spec_drafted > 0
    # ...so the cooldown engaged: some boundaries ran the plain graph
    # (verify boundaries bump both counters, plain ones decode only)
    assert b.verify_steps < b.decode_steps
    assert eng.stats["compiles_after_warmup"] == 0
    assert eng.cache.check_leaks()


def test_spec_kill_switch_and_config_guards(net, warm):
    os.environ["MXTPU_SPEC_DECODE"] = "0"
    try:
        eng = _engine(net)
        assert eng.spec_decode is False
        b = ContinuousBatcher(eng)
        assert b.speculative is False
        # a speculative batcher over a non-speculative engine is a
        # typed config error, not a silent retrace at the first verify
        with pytest.raises(MXNetError):
            ContinuousBatcher(eng, speculative=True)
    finally:
        os.environ.pop("MXTPU_SPEC_DECODE", None)
    # greedy-only: sampling + verification argmax cannot both hold
    with pytest.raises(NotSupportedError):
        InferenceEngine(net, max_batch=3, block_size=8, max_context=16,
                        temperature=0.7, spec_decode=True,
                        compile_cache=_CC)
    # spec_k bounds: engine floor, batcher within compiled widths
    with pytest.raises(MXNetError):
        InferenceEngine(net, max_batch=3, block_size=8, max_context=16,
                        spec_decode=True, spec_k=0, compile_cache=_CC)
    eng = _engine(net, spec_decode=True, spec_k=2)
    with pytest.raises(MXNetError):
        ContinuousBatcher(eng, spec_k=4)   # exceeds compiled widths


# ----------------------------------------------------------------------
# verify() semantics at the engine seam
# ----------------------------------------------------------------------

def test_verify_single_token_rows_match_plain_decode(net, warm):
    """A verify dispatch whose rows carry ONE token each (no drafts) is
    exactly a plain decode step — the mixed-batch contract."""
    eng = _engine(net, spec_decode=True, spec_k=2)
    tok, _ = eng.prefill("a", [1, 2, 3, 1, 2])
    pos = 5
    assert eng.reserve("a", pos)
    out = eng.verify([("a", [int(tok)], pos)])
    eng.release("a")
    # replay plainly on a fresh slot: the same token must come out
    tok2, _ = eng.prefill("b", [1, 2, 3, 1, 2])
    assert int(tok2) == int(tok)
    assert eng.reserve("b", pos)
    nxt, _lg = eng.decode([("b", int(tok2), pos)])
    eng.release("b")
    assert int(out[0, 0]) == int(nxt[0])
    assert eng.stats["compiles_after_warmup"] == 0
    assert eng.cache.check_leaks()


# ----------------------------------------------------------------------
# the PrefixCache draft source: trie continuation
# ----------------------------------------------------------------------

def _pc(block_size=4, num_blocks=16):
    c = PagedKVCache(num_layers=1, num_kv_heads=2, head_dim=8,
                     num_blocks=num_blocks, block_size=block_size,
                     max_batch=2)
    return c, PrefixCache(c)


def test_continuation_walks_chain_and_partial_tail():
    c, pc = _pc()
    toks = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]   # 2 full blocks + partial
    assert c.alloc("a", len(toks))
    pc.insert("a", toks)
    # exact-prefix continuation through full blocks into the partial
    assert pc.continuation([1, 2, 3, 4], 6) == [5, 6, 7, 8, 9, 10]
    # mid-block prefix: the child block's tokens complete it
    assert pc.continuation([1, 2, 3, 4, 5, 6], 4) == [7, 8, 9, 10]
    # k caps the draft
    assert pc.continuation([1, 2, 3, 4], 3) == [5, 6, 7]
    # the partial tail is a LEAF: the walk stops there
    assert pc.continuation([1, 2, 3, 4, 5, 6, 7, 8, 9], 4) == [10]
    assert pc.continuation(toks, 4) == []
    # unknown prefix: nothing
    assert pc.continuation([9, 9, 9, 9], 4) == []
    c.free("a")
    pc.clear()
    assert c.check_leaks()


def test_continuation_is_refcount_and_lru_neutral():
    c, pc = _pc()
    toks = [1, 2, 3, 4, 5, 6]
    assert c.alloc("a", len(toks))
    pc.insert("a", toks)
    refs_before = {b: c.refcount(b) for b in c.table("a")}
    tick = pc._tick
    lookups, hits = pc.lookups, pc.hits
    assert pc.continuation([1, 2, 3, 4], 2) == [5, 6]
    # a draft is a guess, not an adoption: no refs, no LRU churn, no
    # hit accounting (eviction pressure must not see phantom traffic)
    assert {b: c.refcount(b) for b in c.table("a")} == refs_before
    assert pc._tick == tick
    assert (pc.lookups, pc.hits) == (lookups, hits)
    c.free("a")
    pc.clear()
    assert c.check_leaks()


def test_continuation_after_eviction_degrades_to_no_draft():
    c, pc = _pc()
    toks = [1, 2, 3, 4, 5, 6, 7, 8]
    assert c.alloc("a", len(toks))
    pc.insert("a", toks)
    c.free("a")                      # only the chain holds the blocks
    drafted = pc.continuation([1, 2, 3, 4], 4)
    assert drafted == [5, 6, 7, 8]
    # chain evicted mid-draft: the already-returned ints stay valid
    # (a wrong guess just fails acceptance) and a NEW walk finds
    # nothing — no draft, never a crash
    assert pc.evict(blocks_needed=c.num_blocks) > 0
    assert drafted == [5, 6, 7, 8]
    assert pc.continuation([1, 2, 3, 4], 4) == []
    assert pc.held_blocks() == 0
    assert c.check_leaks()


def test_draft_source_prefers_cache_then_ngram():
    c, pc = _pc()
    toks = [1, 2, 3, 4, 5, 6]
    assert c.alloc("a", len(toks))
    pc.insert("a", toks)
    ds = DraftSource(prefix_cache=pc)
    # cache hit: the trie continuation wins
    assert ds.propose([1, 2, 3, 4], 2) == [5, 6]
    assert ds.from_cache == 1 and ds.from_ngram == 0
    # cache miss, self-repeating context: prompt-lookup n-gram fires
    assert ds.propose([7, 8, 9, 7, 8, 9, 7, 8], 3) == [9, 7, 8]
    assert ds.from_ngram == 1
    # nothing to match: no draft
    assert ds.propose([11, 12, 13], 4) == []
    assert ds.propose([5], 4) == []          # too short
    assert ds.propose([7, 8, 9, 7, 8], 0) == []
    c.free("a")
    pc.clear()
    assert c.check_leaks()


def test_ngram_longest_gram_and_recency_win():
    ds = DraftSource()
    # trailing [1,2] occurs twice earlier; the MOST RECENT occurrence
    # (index 3) supplies the continuation [9], not index 0's [5]
    assert ds.propose([1, 2, 5, 1, 2, 9, 1, 2], 1) == [9]
    # the longest matching gram wins: trailing [2,3] matches before
    # the shorter trailing [3] gets a chance
    assert ds.propose([1, 2, 3, 7, 3, 2, 3], 1) == [7]
