"""Elastic membership (ISSUE 8): the epoch-numbered state machine, the
PS join/announce path, the kvstore epoch fence, controller-led reshards
with bitwise continuation parity, and the chaos elastic scenarios —
all deterministic on the simulated 8-device CPU mesh (FakeClock, zero
sleeps)."""
import os
import socket

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import elastic, gluon, parallel
from mxnet_tpu.base import MXNetError
from mxnet_tpu.elastic import (ElasticController, Membership,
                               StaleMembershipEpoch)
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.testing import faults


# ----------------------------------------------------------------------
# membership state machine
# ----------------------------------------------------------------------

def test_membership_death_bumps_epoch_and_emits():
    clock = faults.FakeClock()
    m = Membership([0, 1, 2], now=clock)
    assert m.epoch == 0 and m.ranks == (0, 1, 2)
    ev = m.worker_dead(1)
    assert m.epoch == 1 and m.ranks == (0, 2)
    assert ev.kind == "death" and ev.rank == 1
    assert m.worker_dead(7) is None          # unknown rank: no transition
    assert m.epoch == 1


def test_membership_join_is_two_phase():
    clock = faults.FakeClock(100.0)
    m = Membership([0], now=clock, rendezvous_s=30)
    deadline = m.announce_join(1, seen_epoch=0)
    assert deadline == 130.0
    assert m.state == elastic.RENDEZVOUS and m.pending_join == 1
    assert m.epoch == 0                      # announce does NOT commit
    ev = m.confirm_join(1)
    assert ev.kind == "join" and m.epoch == 1
    assert m.ranks == (0, 1) and m.state == elastic.STABLE


def test_membership_stale_announce_rejected_cleanly():
    m = Membership([0], now=faults.FakeClock())
    m.announce_join(1, seen_epoch=0)
    m.confirm_join(1)                        # epoch -> 1
    with pytest.raises(StaleMembershipEpoch, match="stale membership"):
        m.announce_join(2, seen_epoch=0)
    with pytest.raises(MXNetError, match="already a live member"):
        m.announce_join(1, seen_epoch=m.epoch)


def test_membership_rendezvous_expiry_degrades():
    clock = faults.FakeClock(0.0)
    m = Membership([0], now=clock, rendezvous_s=10)
    m.announce_join(1, seen_epoch=0)
    assert m.poll() is None                  # still inside the window
    clock.advance(10.5)
    ev = m.poll()
    assert ev.kind == "rendezvous_expired" and ev.rank == 1
    assert m.pending_join is None and m.epoch == 0
    with pytest.raises(MXNetError, match="no matching announced join"):
        m.confirm_join(1)


def test_membership_joiner_death_cancels_rendezvous():
    clock = faults.FakeClock()
    m = Membership([0, 1], now=clock)
    m.announce_join(2, seen_epoch=0)
    ev = m.worker_dead(2)                    # the flapping worker
    assert ev.kind == "rendezvous_cancelled"
    assert m.pending_join is None and m.epoch == 0
    assert m.ranks == (0, 1)


def test_membership_check_epoch_fence():
    m = Membership([0, 1])
    m.check_epoch(0)                         # current: fine
    m.worker_dead(1)
    with pytest.raises(StaleMembershipEpoch, match="rejected instead "
                                                   "of deadlocking"):
        m.check_epoch(0)


def test_membership_view_is_jsonable():
    import json
    m = Membership([0, 1], now=faults.FakeClock())
    m.announce_join(2, seen_epoch=0)
    view = json.loads(json.dumps(m.view()))
    assert view == {"epoch": 0, "ranks": [0, 1],
                    "state": "rendezvous", "pending": 2}


# ----------------------------------------------------------------------
# PS join/announce path (satellite: the symmetric twin of the PR 4
# deterministic death-path tests)
# ----------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_ps_join_announce_and_stale_rejection():
    """Rejoin after a heartbeat-detected death: the announce RPC with
    the CURRENT epoch parks the worker in rendezvous; an announce with
    the stale pre-death epoch is rejected with a clean typed error —
    zero wall-clock sleeps anywhere."""
    from mxnet_tpu.kvstore.ps_server import PSServer, PSClient
    clock = faults.FakeClock(1000.0)
    port = _free_port()
    srv = PSServer("127.0.0.1", port, num_workers=2,
                   heartbeat_timeout=5.0)
    srv._now = clock
    membership = Membership([0, 1], now=clock, rendezvous_s=30)
    srv.attach_membership(membership)
    c0 = PSClient("127.0.0.1", port)
    c1 = PSClient("127.0.0.1", port)
    try:
        assert c0.membership() == {"epoch": 0, "ranks": [0, 1],
                                   "state": "stable", "pending": None}
        # death through the heartbeat path commits into the membership
        c0.beat_once(0)
        c1.beat_once(1)
        clock.advance(3.0)
        c0.beat_once(0)
        with faults.inject("ps.heartbeat.drop", action="drop"):
            assert not c1.beat_once(1)
        clock.advance(3.0)
        assert srv._scan_dead() == [1]
        assert membership.epoch == 1 and membership.ranks == (0,)

        # rejoin carrying the PRE-DEATH epoch: rejected cleanly
        with pytest.raises(MXNetError, match="stale membership epoch"):
            c1.join(1, 0)
        assert membership.pending_join is None

        # rejoin with the current epoch: accepted into rendezvous, and
        # the joiner counts as alive again (it just spoke to us)
        view = c1.join(1, membership.epoch)
        assert view["state"] == "rendezvous" and view["pending"] == 1
        assert view["rendezvous_deadline"] == clock() + 30
        assert srv.dead_workers() == []
        assert c0.membership()["pending"] == 1

        # a second, different joiner is refused while one is pending
        with pytest.raises(MXNetError, match="one join at a time"):
            c0.join(5, membership.epoch)

        membership.confirm_join(1)
        assert c0.membership() == {"epoch": 2, "ranks": [0, 1],
                                   "state": "stable", "pending": None}
    finally:
        c0.close()
        c1.close()
        srv._sock.close()


def test_ps_join_without_membership_errors_cleanly():
    from mxnet_tpu.kvstore.ps_server import PSServer, PSClient
    port = _free_port()
    srv = PSServer("127.0.0.1", port, num_workers=1)
    c = PSClient("127.0.0.1", port)
    try:
        assert c.membership()["epoch"] is None
        with pytest.raises(MXNetError, match="no membership attached"):
            c.join(0, 0)
    finally:
        c.close()
        srv._sock.close()


# ----------------------------------------------------------------------
# kvstore epoch fence: stale collectives are rejected, not deadlocked
# ----------------------------------------------------------------------

def test_kvstore_pushpull_fenced_by_membership_epoch():
    kv = mx.kv.create("tpu_sync")
    kv.init("w", mx.nd.zeros((4,)))
    membership = Membership([0, 1])
    kv.attach_membership(membership)
    out = mx.nd.zeros((4,))
    kv.pushpull("w", mx.nd.ones((4,)), out=out)      # current epoch: ok
    membership.worker_dead(1)                        # cluster moves on
    with pytest.raises(StaleMembershipEpoch,
                       match="membership epoch 0 .* cluster is at 1"):
        kv.pushpull("w", mx.nd.ones((4,)), out=out)
    with pytest.raises(StaleMembershipEpoch):
        kv.push("w", mx.nd.ones((4,)))
    assert kv.refresh_membership() == 1              # post-reshard re-arm
    kv.pushpull("w", mx.nd.ones((4,)), out=out)


# ----------------------------------------------------------------------
# controller-led reshard: parity, floors, kill switch
# ----------------------------------------------------------------------

def _build_dp(mesh, seed=1234):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.Dense(4)
    net.initialize()
    trainer = parallel.DataParallelTrainer(
        net, gluon.loss.L2Loss(), "adam", {"learning_rate": 0.05},
        mesh=mesh, shard_updates=True)
    return net, trainer


def _data(n=6):
    rng = np.random.RandomState(0)
    return (rng.randn(n, 16, 8).astype(np.float32),
            rng.randn(n, 16, 4).astype(np.float32))


def test_controller_shrink_reshard_is_bitwise_vs_fresh_restore():
    """dp 8 -> 4 mid-run: the in-place reshard must land EXACTLY the
    state a fresh dp=4 process restored from the same instant would
    reach — the acceptance bar's parity contract."""
    import jax
    from mxnet_tpu.checkpoint import _rng_state, _restore_rng
    devices = jax.devices()
    xs, ys = _data()
    net, trainer = _build_dp(make_mesh({"dp": 8}, devices))
    clock = faults.FakeClock()
    membership = Membership([0, 1], now=clock)
    ctrl = ElasticController(membership, devices=devices,
                             devices_per_worker=4, net=net,
                             backoff_s=0.0, now=clock,
                             sleep=lambda s: None)
    for i in range(3):
        trainer.step(mx.nd.array(xs[i]), mx.nd.array(ys[i]))
    assert ctrl.check_step(3, trainer, net) is None   # no transition yet
    # boundary snapshot = what a fresh process would restore
    sd = trainer.state_dict()
    sd = {"arrays": {k: mx.nd.array(v.asnumpy())
                     for k, v in sd["arrays"].items()},
          "meta": dict(sd["meta"])}
    psnap = {n_: p.data().asnumpy().copy() for n_, p
             in net._collect_params_with_prefix().items()}
    rng_arrays, rng_meta = _rng_state()
    rng_arrays = {k: mx.nd.array(v.asnumpy())
                  for k, v in rng_arrays.items()}

    membership.worker_dead(1)
    ev = ctrl.check_step(3, trainer, net)
    assert ev["source"] == "peer" and ev["dp"] == 4
    assert trainer.mesh.shape["dp"] == 4
    assert ctrl.stats()["transitions"] == 1
    assert ctrl.stats()["reshard_ms"] is not None
    for i in range(3, 6):
        trainer.step(mx.nd.array(xs[i]), mx.nd.array(ys[i]))

    ref_net, ref_trainer = _build_dp(make_mesh({"dp": 4}, devices[:4]),
                                     seed=999)
    ref_net(mx.nd.array(xs[0]))
    target = ref_net._collect_params_with_prefix()
    for n_, v in psnap.items():
        target[n_].set_data(v)
    ref_trainer.load_state_dict(sd)
    _restore_rng(rng_arrays, rng_meta)
    for i in range(3, 6):
        ref_trainer.step(mx.nd.array(xs[i]), mx.nd.array(ys[i]))

    for n_, p in net._collect_params_with_prefix().items():
        assert np.array_equal(p.data().asnumpy(),
                              target[n_].data().asnumpy()), n_
    a = {k: v.asnumpy() for k, v in trainer.state_dict()
         ["arrays"].items()}
    b = {k: v.asnumpy() for k, v in ref_trainer.state_dict()
         ["arrays"].items()}
    assert set(a) == set(b)
    for k in a:
        assert np.array_equal(a[k], b[k]), k


def test_controller_refuses_to_shrink_below_min_dp():
    import jax
    devices = jax.devices()
    xs, ys = _data(1)
    net, trainer = _build_dp(make_mesh({"dp": 8}, devices))
    trainer.step(mx.nd.array(xs[0]), mx.nd.array(ys[0]))
    membership = Membership([0, 1], now=faults.FakeClock())
    ctrl = ElasticController(membership, devices=devices,
                             devices_per_worker=4, net=net, min_dp=8,
                             backoff_s=0.0, sleep=lambda s: None)
    membership.worker_dead(1)
    with pytest.raises(MXNetError, match="below the MXTPU_ELASTIC_"
                                         "MIN_DP"):
        ctrl.check_step(1, trainer, net)


def test_controller_kill_switch(monkeypatch):
    monkeypatch.setenv("MXTPU_ELASTIC", "0")
    membership = Membership([0, 1], now=faults.FakeClock())
    ctrl = ElasticController(membership, devices_per_worker=4)
    membership.worker_dead(1)
    # inert: no transition applied, no trainer touched
    assert ctrl.check_step(1, trainer=None, params=None) is None
    assert ctrl.pending() is False


def test_reshard_fault_falls_back_to_checkpoint(tmp_path):
    """Kill the peer transfer on every retry: the controller recovers
    from the newest valid checkpoint and reports the rewind step."""
    import jax
    from mxnet_tpu.checkpoint import CheckpointManager
    devices = jax.devices()
    xs, ys = _data()
    net, trainer = _build_dp(make_mesh({"dp": 8}, devices))
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=3,
                            async_save=False)
    for i in range(3):
        trainer.step(mx.nd.array(xs[i]), mx.nd.array(ys[i]))
    mgr.save(3, params=net, trainer=trainer, iterator={"batch": 3})
    membership = Membership([0, 1], now=faults.FakeClock())
    ctrl = ElasticController(membership, devices=devices,
                             devices_per_worker=4, net=net,
                             checkpoint_manager=mgr, max_retries=1,
                             backoff_s=0.0, sleep=lambda s: None)
    membership.worker_dead(1)
    with faults.inject("elastic.reshard"):
        ev = ctrl.check_step(3, trainer, net)
    assert ev["source"] == "checkpoint" and ev["step"] == 3
    assert trainer.mesh.shape["dp"] == 4
    trainer.step(mx.nd.array(xs[3]), mx.nd.array(ys[3]))


def test_reshard_fault_without_checkpoint_raises_both_paths():
    import jax
    devices = jax.devices()
    xs, ys = _data(1)
    net, trainer = _build_dp(make_mesh({"dp": 8}, devices))
    trainer.step(mx.nd.array(xs[0]), mx.nd.array(ys[0]))
    membership = Membership([0, 1], now=faults.FakeClock())
    ctrl = ElasticController(membership, devices=devices,
                             devices_per_worker=4, net=net,
                             max_retries=0, backoff_s=0.0,
                             sleep=lambda s: None)
    membership.worker_dead(1)
    with faults.inject("elastic.reshard"):
        with pytest.raises(MXNetError, match="both paths"):
            ctrl.check_step(1, trainer, net)


# ----------------------------------------------------------------------
# trainer rebuild seam
# ----------------------------------------------------------------------

def test_trainer_rebuild_crosses_dp_one():
    """shard_updates survives a rebuild through dp=1 (where ZeRO-1 is
    inert) and back up."""
    import jax
    devices = jax.devices()
    xs, ys = _data(3)
    net, trainer = _build_dp(make_mesh({"dp": 8}, devices))
    trainer.step(mx.nd.array(xs[0]), mx.nd.array(ys[0]))
    assert trainer._zero1_active()
    sd = trainer.state_dict()
    trainer.rebuild(make_mesh({"dp": 1}, devices[:1]))
    trainer.load_state_dict(sd)
    assert not trainer._zero1_active()
    trainer.step(mx.nd.array(xs[1]), mx.nd.array(ys[1]))
    sd = trainer.state_dict()
    trainer.rebuild(make_mesh({"dp": 8}, devices))
    trainer.load_state_dict(sd)
    assert trainer._zero1_active()
    trainer.step(mx.nd.array(xs[2]), mx.nd.array(ys[2]))


def test_overlap_scheduler_reset_plan():
    from mxnet_tpu.parallel.overlap import OverlapScheduler
    net = gluon.nn.Dense(4)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .randn(4, 8).astype(np.float32))
    params = list(net.collect_params().values())
    sched = OverlapScheduler(params).install()
    try:
        from mxnet_tpu import autograd
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        sched.finish()                     # first cycle builds the plan
        assert sched.plan is not None
        sched.reset_plan()
        assert sched.plan is None          # next cycle re-observes
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        sched.finish()
        assert sched.plan is not None
    finally:
        sched.remove()


# ----------------------------------------------------------------------
# the chaos elastic scenarios, wired into tier-1 (fast, deterministic)
# ----------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["shrink", "grow", "reshard_fault"])
def test_chaos_elastic_scenario(kind, tmp_path):
    from mxnet_tpu.testing.chaos import run_elastic_scenario
    r = run_elastic_scenario(kind, workdir=str(tmp_path))
    assert r["params_bitwise"], r
    assert r["state_bitwise"], r
    assert r["ok"], r


# ----------------------------------------------------------------------
# estimator pause/resume hook
# ----------------------------------------------------------------------

def test_estimator_elastic_pause_reshard_resume():
    import jax
    from mxnet_tpu import metric as metric_mod
    from mxnet_tpu.gluon.contrib.estimator import Estimator, BatchEnd
    devices = jax.devices()
    xs, ys = _data()
    net, trainer = _build_dp(make_mesh({"dp": 8}, devices))
    membership = Membership([0, 1], now=faults.FakeClock())
    ctrl = ElasticController(membership, devices=devices,
                             devices_per_worker=4, net=net,
                             backoff_s=0.0, sleep=lambda s: None)
    batches = [(mx.nd.array(xs[i]), mx.nd.array(ys[i]))
               for i in range(6)]

    class KillAt(BatchEnd):
        def batch_end(self, estimator, *args, **kwargs):
            if estimator.global_step + 1 == 3 and membership.epoch == 0:
                membership.worker_dead(1)

    est = Estimator(net, gluon.loss.L2Loss(),
                    train_metrics=[metric_mod.Loss()], trainer=trainer)
    est.fit(batches, epochs=1, event_handlers=[KillAt()],
            elastic_controller=ctrl)
    assert not est.preempted                    # peer path: no rewind
    assert est.global_step == 6
    assert trainer.mesh.shape["dp"] == 4
    assert ctrl.stats()["transitions"] == 1
    assert ctrl.stats()["membership_epoch"] == 1


def test_estimator_elastic_checkpoint_fallback_stops_cleanly(tmp_path):
    """When the peer transfer dies, the estimator adopts the PR 4
    preemption contract: restore from the checkpoint, stop with
    ``.preempted`` set, and a re-entry with resume='auto' replays."""
    import jax
    from mxnet_tpu import metric as metric_mod
    from mxnet_tpu.checkpoint import CheckpointManager
    from mxnet_tpu.gluon.contrib.estimator import Estimator, BatchEnd
    devices = jax.devices()
    xs, ys = _data()
    net, trainer = _build_dp(make_mesh({"dp": 8}, devices))
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=5,
                            async_save=False)
    membership = Membership([0, 1], now=faults.FakeClock())
    ctrl = ElasticController(membership, devices=devices,
                             devices_per_worker=4, net=net,
                             checkpoint_manager=mgr, max_retries=0,
                             backoff_s=0.0, sleep=lambda s: None)
    batches = [(mx.nd.array(xs[i]), mx.nd.array(ys[i]))
               for i in range(6)]

    class KillAt(BatchEnd):
        def batch_end(self, estimator, *args, **kwargs):
            if estimator.global_step + 1 == 3 and membership.epoch == 0:
                membership.worker_dead(1)

    est = Estimator(net, gluon.loss.L2Loss(),
                    train_metrics=[metric_mod.Loss()], trainer=trainer)
    with faults.inject("elastic.reshard"):
        est.fit(batches, epochs=1, event_handlers=[KillAt()],
                checkpoint_manager=mgr, checkpoint_every=1,
                elastic_controller=ctrl)
    assert est.preempted                        # fallback: clean stop
    # rewound to the last DURABLE boundary: the step-2 save would have
    # happened after this boundary's elastic check, so the newest valid
    # checkpoint is step 1
    assert est.global_step == 1
    assert trainer.mesh.shape["dp"] == 4
    # re-entry resumes from the restored cursor and completes
    est.fit(batches, epochs=1, resume="auto", checkpoint_manager=mgr,
            elastic_controller=ctrl)
    assert est.global_step == 6 and not est.preempted
