"""NDArray semantics tests.

Modelled on reference tests/python/unittest/test_ndarray.py (SURVEY.md §4).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal, with_seed


def test_creation_defaults():
    a = nd.array([[1, 2], [3, 4]])
    assert a.dtype == np.float32  # python lists default to float32
    assert a.shape == (2, 2)
    b = nd.array(np.arange(6, dtype=np.int32).reshape(2, 3))
    assert b.dtype == np.int32    # numpy dtype preserved
    z = nd.zeros((2, 3))
    assert z.dtype == np.float32
    assert (z.asnumpy() == 0).all()
    o = nd.ones(4)
    assert o.shape == (4,)
    f = nd.full((2, 2), 7.5)
    assert (f.asnumpy() == 7.5).all()
    r = nd.arange(0, 10, 2)
    assert_almost_equal(r, np.arange(0, 10, 2, dtype=np.float32))


def test_elementwise_arith():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert_almost_equal(a + b, np.array([[6, 8], [10, 12]], np.float32))
    assert_almost_equal(a - b, -np.array([[4, 4], [4, 4]], np.float32))
    assert_almost_equal(a * b, np.array([[5, 12], [21, 32]], np.float32))
    assert_almost_equal(b / a, np.array([[5, 3], [7 / 3, 2]], np.float32))
    assert_almost_equal(a ** 2, np.array([[1, 4], [9, 16]], np.float32))
    assert_almost_equal(2 + a, a.asnumpy() + 2)
    assert_almost_equal(2 - a, 2 - a.asnumpy())
    assert_almost_equal(2 / a, 2 / a.asnumpy())
    assert_almost_equal(-a, -a.asnumpy())


def test_inplace_ops():
    a = nd.ones((2, 2))
    a += 1
    assert (a.asnumpy() == 2).all()
    a *= 3
    assert (a.asnumpy() == 6).all()
    a /= 2
    assert (a.asnumpy() == 3).all()
    a -= 1
    assert (a.asnumpy() == 2).all()


def test_setitem_getitem():
    a = nd.zeros((3, 4))
    a[1] = 5.0
    assert (a.asnumpy()[1] == 5).all()
    a[0, 2] = 7.0
    assert a.asnumpy()[0, 2] == 7
    a[:, 1] = 2.0
    assert (a.asnumpy()[:, 1] == 2).all()
    b = a[1:3]
    assert b.shape == (2, 4)
    # fancy index with NDArray
    idx = nd.array([0, 2], dtype="int32")
    c = a[idx]
    assert c.shape == (2, 4)


def test_reshape_codes():
    a = nd.zeros((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)  # 0 copies dim
    assert a.reshape(0, 0, -1).shape == (2, 3, 4)
    with pytest.raises(mx.MXNetError):
        a.reshape((-2, 4))


def test_flatten_is_mxnet_flatten():
    a = nd.zeros((2, 3, 4))
    assert a.flatten().shape == (2, 12)  # NOT numpy ravel


def test_broadcast():
    a = nd.array([[1.0], [2.0]])
    out = a.broadcast_to((2, 3))
    assert out.shape == (2, 3)
    assert_almost_equal(out, np.broadcast_to(a.asnumpy(), (2, 3)))
    with pytest.raises(mx.MXNetError):
        nd.zeros((2, 2)).broadcast_to((3, 3))


def test_reductions():
    a = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    npa = a.asnumpy()
    assert_almost_equal(a.sum(), npa.sum())
    assert_almost_equal(a.sum(axis=1), npa.sum(1))
    assert_almost_equal(a.mean(axis=(0, 2)), npa.mean((0, 2)))
    assert_almost_equal(a.max(axis=2, keepdims=True), npa.max(2, keepdims=True))
    assert_almost_equal(a.min(), npa.min())
    assert_almost_equal(nd.norm(a), np.sqrt((npa ** 2).sum()))
    assert_almost_equal(a.argmax(axis=1), npa.argmax(1).astype(np.float32))


def test_dot_semantics():
    # mx.nd.dot on >2d: tensordot over last/first axes, not matmul batching
    a = nd.array(np.random.rand(2, 3).astype(np.float32))
    b = nd.array(np.random.rand(3, 4).astype(np.float32))
    assert_almost_equal(nd.dot(a, b), a.asnumpy() @ b.asnumpy())
    assert_almost_equal(nd.dot(a, b, transpose_b=False, transpose_a=False),
                        a.asnumpy() @ b.asnumpy())
    c = nd.array(np.random.rand(4, 3).astype(np.float32))
    assert_almost_equal(nd.dot(a, c, transpose_b=True),
                        a.asnumpy() @ c.asnumpy().T)
    # batch_dot
    x = nd.array(np.random.rand(5, 2, 3).astype(np.float32))
    y = nd.array(np.random.rand(5, 3, 4).astype(np.float32))
    assert_almost_equal(nd.batch_dot(x, y),
                        np.matmul(x.asnumpy(), y.asnumpy()))


def test_concat_stack_split():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    c2 = nd.concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(c2, num_outputs=2, axis=1)
    assert parts[0].shape == (2, 3)
    parts2 = nd.split(nd.ones((4, 6)), num_outputs=2, axis=0,
                      squeeze_axis=False)
    assert parts2[1].shape == (2, 6)


def test_comparison_ops():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([3.0, 2.0, 1.0])
    assert_almost_equal(a == b, np.array([0, 1, 0], np.float32))
    assert_almost_equal(a > b, np.array([0, 0, 1], np.float32))
    assert_almost_equal(a <= b, np.array([1, 1, 0], np.float32))


def test_astype_copy_context():
    a = nd.array([1, 2, 3])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.copy()
    c += 1
    assert (a.asnumpy() == [1, 2, 3]).all()
    d = a.as_in_context(mx.cpu())
    assert d.context.device_type == "cpu"


def test_scalar_conversions():
    a = nd.array([3.5])
    assert a.asscalar() == 3.5
    assert float(a.asnumpy()) == 3.5
    with pytest.raises(mx.MXNetError):
        nd.zeros((2, 2)).asscalar()
    assert bool(nd.array([1.0]))
    assert len(nd.zeros((5, 2))) == 5


def test_save_load_roundtrip(tmp_path):
    fname = str(tmp_path / "arrays.params")
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.arange(5, dtype=np.int32))
    nd.save(fname, {"a": a, "b": b})
    loaded = nd.load(fname)
    assert set(loaded) == {"a", "b"}
    assert_almost_equal(loaded["a"], a)
    assert (loaded["b"].asnumpy() == b.asnumpy()).all()
    # list form
    nd.save(fname, [a, b])
    lst = nd.load(fname)
    assert isinstance(lst, list) and len(lst) == 2


def test_legacy_ndarray_v2_load(tmp_path):
    """Write a reference-format blob by hand and load it
    (src/ndarray/ndarray.cc NDARRAY_V2 layout)."""
    import struct
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    blob = struct.pack("<Q", 0x112) + struct.pack("<Q", 0)
    blob += struct.pack("<Q", 1)  # count
    blob += struct.pack("<I", 0xF993FAC9)  # NDARRAY_V2 magic
    blob += struct.pack("<i", -1)  # dense stype
    blob += struct.pack("<I", 2)  # ndim
    blob += struct.pack("<qq", 2, 3)
    blob += struct.pack("<II", 1, 0)  # ctx
    blob += struct.pack("<I", 0)  # float32
    blob += arr.tobytes()
    blob += struct.pack("<Q", 1)  # one name
    blob += struct.pack("<Q", len(b"weight")) + b"weight"
    fname = str(tmp_path / "legacy.params")
    with open(fname, "wb") as f:
        f.write(blob)
    loaded = nd.load(fname)
    assert set(loaded) == {"weight"}
    assert_almost_equal(loaded["weight"], arr)


@with_seed()
def test_random_moments():
    u = nd.random.uniform(0, 1, shape=(10000,))
    assert 0.45 < float(u.mean().asscalar()) < 0.55
    n = nd.random.normal(0, 1, shape=(10000,))
    assert abs(float(n.mean().asscalar())) < 0.1
    assert 0.9 < float(((n - n.mean()) ** 2).mean().asscalar()) < 1.1
    r = nd.random.randint(0, 10, shape=(1000,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10


@with_seed()
def test_random_seed_reproducible():
    mx.random.seed(42)
    a = nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    b = nd.random.uniform(shape=(5,)).asnumpy()
    assert (a == b).all()


def test_take_pick_onehot():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    t = nd.take(a, nd.array([0, 2]))
    assert t.shape == (2, 4)
    assert_almost_equal(t, a.asnumpy()[[0, 2]])
    p = nd.pick(a, nd.array([1, 0, 3]), axis=1)
    assert_almost_equal(p, np.array([1, 4, 11], np.float32))
    oh = nd.one_hot(nd.array([0, 2]), 4)
    assert_almost_equal(oh, np.eye(4, dtype=np.float32)[[0, 2]])


def test_topk_sort_argsort():
    a = nd.array([[3.0, 1.0, 2.0], [0.5, 2.5, 1.5]])
    idx = nd.topk(a, k=2)
    assert idx.shape == (2, 2)
    assert (idx.asnumpy()[0] == [0, 2]).all()
    vals = nd.topk(a, k=1, ret_typ="value")
    assert_almost_equal(vals, np.array([[3.0], [2.5]], np.float32))
    s = nd.sort(a, axis=1)
    assert_almost_equal(s, np.sort(a.asnumpy(), 1))
    ags = nd.argsort(a, axis=1)
    assert_almost_equal(ags, np.argsort(a.asnumpy(), 1).astype(np.float32))


def test_where_clip_misc():
    cond = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([10.0, 20.0, 30.0])
    assert_almost_equal(nd.where(cond, x, y), np.array([1, 20, 3], np.float32))
    assert_almost_equal(nd.clip(y, 15, 25), np.array([15, 20, 25], np.float32))
    assert_almost_equal(nd.abs(nd.array([-1.0, 2.0])), [1, 2])


def test_context_api():
    assert mx.cpu(0) == mx.cpu(0)
    assert mx.cpu(0) != mx.tpu(0) or mx.context.num_tpus() == 0
    with mx.Context("cpu", 0):
        a = nd.zeros((2,))
        assert a.context.device_type == "cpu"
    assert str(mx.cpu(1)) == "cpu(1)"


def test_norm_ord():
    import numpy as np
    import mxnet_tpu as mx
    x = mx.nd.array([[3.0, -4.0]])
    assert abs(float(mx.nd.norm(x, ord=1).asnumpy()) - 7.0) < 1e-6
    assert abs(float(mx.nd.norm(x, ord=2).asnumpy()) - 5.0) < 1e-6
    assert abs(float(mx.nd.norm(x).asnumpy()) - 5.0) < 1e-6


def test_global_pool_sum():
    import numpy as np
    import mxnet_tpu as mx
    x = mx.nd.ones((1, 1, 4, 4))
    out = mx.nd.Pooling(x, pool_type="sum", global_pool=True)
    assert abs(float(out.asnumpy().ravel()[0]) - 16.0) < 1e-6
    out = mx.nd.Pooling(x, pool_type="avg", global_pool=True)
    assert abs(float(out.asnumpy().ravel()[0]) - 1.0) < 1e-6


def test_registry_driven_method_surface():
    """Reference autogen parity: op registry entries exposed as NDArray
    methods, forwarding to the tape-integrated ops."""
    import numpy as np
    from mxnet_tpu import autograd
    a = nd.array(np.array([[4.0, 1.0], [9.0, 16.0]]))
    for name in ["flip", "diag", "sort", "argsort", "sign", "round",
                 "ceil", "floor", "square", "rsqrt", "log2", "sin",
                 "cos", "tan", "sinh", "pad", "batch_dot", "nansum",
                 "moments", "shape_array", "tile", "norm", "degrees",
                 "radians", "tostype", "slice"]:
        assert hasattr(a, name), name
    np.testing.assert_allclose(a.square().asnumpy(), a.asnumpy() ** 2)
    np.testing.assert_allclose(a.sort().asnumpy(), np.sort(a.asnumpy()))
    np.testing.assert_allclose(
        nd.array([np.pi]).degrees().asnumpy(), [180.0], rtol=1e-6)
    # the method form records on the tape exactly like the op form
    a.attach_grad()
    with autograd.record():
        y = a.square().sum()
    y.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), 2 * a.asnumpy())
    # dense -> sparse storage conversion
    from mxnet_tpu.ndarray import sparse as sp
    r = a.tostype("row_sparse")
    assert isinstance(r, sp.RowSparseNDArray)
    np.testing.assert_allclose(r.asnumpy(), a.asnumpy())
    c = a.tostype("csr")
    assert isinstance(c, sp.CSRNDArray)


def test_boolean_mask_indexing():
    """bool-DTYPE NDArray keys mask (np-compat); float comparison
    results keep the legacy integer-gather semantics (reference mx.nd
    comparisons return float 0/1 and never meant masking)."""
    import numpy as np
    a = nd.array(np.arange(24.0).reshape(4, 6))
    mask = (a > 10).astype("bool")
    np.testing.assert_allclose(a[mask].asnumpy(),
                               np.arange(24.0)[np.arange(24.0) > 10])
    b = nd.array(np.arange(6.0))
    b[(b > 3).astype("bool")] = 0.0
    np.testing.assert_allclose(b.asnumpy(), [0, 1, 2, 3, 0, 0])
    # numpy bool keys work directly
    assert a[np.array([True, False, True, False])].shape == (2, 6)
    # a bool mask inside jit has a data-dependent shape -> clear error
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.ndarray.ndarray import NDArray

    def traced(d, m):
        return NDArray(d)[NDArray(m)]

    with pytest.raises(mx.MXNetError):
        jax.jit(lambda d, m: traced(d, m).data)(
            a.data, mask.data)
