"""Module API (legacy symbolic trainer) — reference:
tests/python/unittest/test_module.py + tests/python/train/test_mlp.py
(the convergence smoke test, SURVEY.md §4 technique 5)."""
import numpy as np
import pytest

import mxnet_tpu as mx

nd = mx.nd


def _toy_symbol():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _toy_iter(n=240, batch=24, seed=0):
    # class centers are FIXED (seed 1234) so train/val draws share the task;
    # `seed` only varies the noise/label draw
    centers = np.random.RandomState(1234).randn(3, 8) * 3
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 3, n)
    data = centers[labels] + rng.randn(n, 8) * 0.3
    return mx.io.NDArrayIter(data.astype(np.float32),
                             labels.astype(np.float32), batch,
                             shuffle=True, label_name="softmax_label")


def test_module_bind_forward_shapes():
    mod = mx.mod.Module(_toy_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    batch = mx.io.DataBatch(data=[nd.random.uniform(shape=(4, 8))],
                            label=[nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (4, 3)
    np.testing.assert_allclose(out.asnumpy().sum(-1), 1.0, rtol=1e-5)


def test_module_fit_converges():
    """tests/python/train/test_mlp.py pattern: fit then assert accuracy."""
    mod = mx.mod.Module(_toy_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    train = _toy_iter(seed=0)
    val = _toy_iter(seed=1)
    mod.fit(train, eval_data=val, num_epoch=10,
            initializer=mx.init.Xavier(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    m = mx.metric.Accuracy()
    mod.score(val, m)
    assert m.get()[1] > 0.9


def test_module_checkpoint_roundtrip(tmp_path):
    mod = mx.mod.Module(_toy_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 3)
    sym, arg, aux = mx.mod.load_checkpoint(prefix, 3)
    assert set(arg) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
    mod2 = mx.mod.Module(_toy_symbol(), data_names=("data",),
                         label_names=("softmax_label",))
    mod2.bind(data_shapes=[("data", (4, 8))],
              label_shapes=[("softmax_label", (4,))])
    mod2.set_params(arg, aux)
    batch = mx.io.DataBatch(data=[nd.ones((4, 8))], label=[nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               mod2.get_outputs()[0].asnumpy(), rtol=1e-6)


def test_module_predict():
    mod = mx.mod.Module(_toy_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    it = _toy_iter()
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    preds = mod.predict(it)
    assert preds.shape[1] == 3


def test_bucketing_module_varlen():
    """BucketingModule (python/mxnet/module/bucketing_module.py): one module
    per bucket, params shared."""
    def sym_gen(seq_len):
        # per-timestep FC (flatten=False): weight shape is length-
        # independent, so buckets share it — the reference's RNN pattern
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc_shared",
                                   flatten=False)
        pooled = mx.sym.mean(fc, axis=1, name="pool")
        out = mx.sym.SoftmaxOutput(pooled, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16)
    mod.bind(data_shapes=[("data", (2, 16, 6))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    # switch bucket: shorter sequence reuses the same weights
    mod.switch_bucket(8, data_shapes=[("data", (2, 8, 6))],
                      label_shapes=[("softmax_label", (2,))])
    batch = mx.io.DataBatch(data=[nd.ones((2, 8, 6))],
                            label=[nd.zeros((2,))], bucket_key=8)
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (2, 4)


@pytest.mark.skipif(len(__import__("jax").devices()) < 8,
                    reason="needs 8 virtual devices")
@pytest.mark.slow   # slow-marked (ISSUE 18 tier-1 headroom): legacy
# Module-API dp split; the gluon/parallel dp paths (test_mesh3d,
# test_data_parallel) keep multi-device execution tier-1
def test_module_multi_device_data_parallel():
    """ctx=[cpu(0)..cpu(7)] forms a dp mesh: params replicated, batch
    sharded — the DataParallelExecutorGroup role (reference
    module/executor_group.py, SURVEY.md §3.4). Same task must converge
    and score like the single-device module."""
    ctxs = [mx.context.Context("cpu", i) for i in range(8)]
    mod = mx.mod.Module(_toy_symbol(), data_names=("data",),
                        label_names=("softmax_label",), context=ctxs)
    train = _toy_iter(seed=0)
    val = _toy_iter(seed=1)
    mod.fit(train, eval_data=val, num_epoch=10,
            initializer=mx.init.Xavier(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    assert mod._mesh is not None and mod._mesh.shape["dp"] == 8
    m = mx.metric.Accuracy()
    mod.score(val, m)
    assert m.get()[1] > 0.9


def test_module_multi_device_batch_divisibility():
    ctxs = [mx.context.Context("cpu", i) for i in range(3)]
    mod = mx.mod.Module(_toy_symbol(), data_names=("data",),
                        label_names=("softmax_label",), context=ctxs)
    with pytest.raises(mx.MXNetError):
        mod.bind(data_shapes=[("data", (4, 8))],
                 label_shapes=[("softmax_label", (4,))])


@pytest.mark.slow
def test_mnist_convergence_floor():
    """BASELINE correctness floor (SURVEY.md §4.5, reference
    tests/python/train/test_mlp.py): MLP on MNIST must reach >0.98
    accuracy in <5 epochs. Runs on the synthetic MNIST unless
    MXTPU_REAL_DATA=1 (no network in CI)."""
    import os
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn
    if not os.environ.get("MXTPU_REAL_DATA"):
        os.environ.setdefault("MXTPU_SYNTHETIC_DATA", "1")
    train_set = gluon.data.vision.MNIST(train=True)
    val_set = gluon.data.vision.MNIST(train=False)
    tf = gluon.data.vision.transforms.ToTensor()
    train_data = gluon.data.DataLoader(
        train_set.transform_first(tf), batch_size=100, shuffle=True)
    val_data = gluon.data.DataLoader(
        val_set.transform_first(tf), batch_size=100)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Flatten(), nn.Dense(128, activation="relu"),
                nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    # lr 0.01: the synthetic class-separable set diverges with lr>=0.05 +
    # momentum (verified against pure jax — optimization, not framework)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(5):
        for data, label in train_data:
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(data.shape[0])
    metric = mx.metric.Accuracy()
    for data, label in val_data:
        metric.update([label], [net(data)])
    assert metric.get()[1] > 0.98, f"val acc {metric.get()[1]}"


def test_module_load_applies_checkpoint(tmp_path):
    """Module.load -> bind -> init_params must score like the saved model;
    before r3 the checkpoint was stashed and silently re-initialized
    (VERDICT r2 missing #4b). Reference: Module.load(prefix, epoch)."""
    mod = mx.mod.Module(_toy_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    train = _toy_iter(seed=0)
    mod.fit(train, num_epoch=5, initializer=mx.init.Xavier(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    val = _toy_iter(seed=1)
    m = mx.metric.Accuracy()
    mod.score(val, m)
    trained_acc = m.get()[1]
    prefix = str(tmp_path / "ckpt")
    mod.save_checkpoint(prefix, 5)

    mod2 = mx.mod.Module.load(prefix, 5, data_names=("data",),
                              label_names=("softmax_label",))
    mod2.bind(data_shapes=[("data", (24, 8))],
              label_shapes=[("softmax_label", (24,))])
    mod2.init_params()    # must apply the loaded params, not re-init
    m2 = mx.metric.Accuracy()
    mod2.score(val, m2)
    assert m2.get()[1] == pytest.approx(trained_acc, abs=1e-6)


def test_module_update_routes_through_kvstore():
    """kvstore='local' fit must apply updates THROUGH the store (server-side
    optimizer, reference kvstore_dist_server.h DataHandleEx semantics) and
    match the no-kvstore run bit-for-bit."""
    runs = {}
    for kv in (None, "local"):
        np.random.seed(7)   # NDArrayIter(shuffle=True) uses the global RNG
        mod = mx.mod.Module(_toy_symbol(), data_names=("data",),
                            label_names=("softmax_label",))
        train = _toy_iter(seed=0)
        mod.fit(train, num_epoch=3,
                initializer=mx.init.Constant(0.05), kvstore=kv,
                optimizer="sgd", optimizer_params={"learning_rate": 0.5})
        runs[kv] = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in runs[None]:
        np.testing.assert_allclose(runs[None][k], runs["local"][k],
                                   rtol=1e-6, err_msg=k)
    # and the store really was in the loop
    assert mod._kvstore is not None and mod._update_on_kvstore


@pytest.mark.slow
def test_module_fit_dist_2proc(tmp_path):
    """2-process Module.fit over dist_sync: ranks train on DIFFERENT data
    shards yet must end with identical weights (r2 missing #4a: update()
    used to skip the kvstore and silently train divergent models)."""
    import os
    import subprocess
    import sys
    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_sync')\n"
        "rank = kv.rank\n"
        "centers = np.random.RandomState(1234).randn(3, 8) * 3\n"
        "rng = np.random.RandomState(rank)  # DIFFERENT data per rank\n"
        "labels = rng.randint(0, 3, 96)\n"
        "data = (centers[labels] + rng.randn(96, 8) * 0.3)\n"
        "it = mx.io.NDArrayIter(data.astype(np.float32),\n"
        "                       labels.astype(np.float32), 24,\n"
        "                       label_name='softmax_label')\n"
        "data_sym = mx.sym.var('data')\n"
        "fc1 = mx.sym.FullyConnected(data_sym, num_hidden=16, name='fc1')\n"
        "act = mx.sym.Activation(fc1, act_type='relu', name='relu1')\n"
        "fc2 = mx.sym.FullyConnected(act, num_hidden=3, name='fc2')\n"
        "sym = mx.sym.SoftmaxOutput(fc2, name='softmax')\n"
        "mod = mx.mod.Module(sym, data_names=('data',),\n"
        "                    label_names=('softmax_label',))\n"
        "np.random.seed(100 + rank)  # init would diverge w/o broadcast\n"
        "mod.fit(it, num_epoch=2, kvstore=kv,\n"
        "        optimizer='sgd',\n"
        "        optimizer_params={'learning_rate': 0.1})\n"
        "args, _ = mod.get_params()\n"
        "digest = float(sum(np.abs(v.asnumpy()).sum()\n"
        "               for v in args.values()))\n"
        "print(f'WORKER_DIGEST {rank} {digest:.10f}')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and ".axon_site" not in p] + [REPO])
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr + r.stdout
    import re
    digests = dict(re.findall(r"WORKER_DIGEST (\d+) ([0-9.]+)", r.stdout))
    assert len(digests) == 2, r.stdout + r.stderr
    assert digests["0"] == digests["1"], digests


def test_symbol_json_roundtrip_rebuilds_module(tmp_path):
    """Symbol.load(tojson()) -> executable graph: load_checkpoint rebuilds
    a scoring Module WITHOUT the original model script (r2 missing #5).
    Reference: Symbol.load/load_json -> GraphExecutor (SURVEY.md §5.4)."""
    mod = mx.mod.Module(_toy_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    train = _toy_iter(seed=0)
    mod.fit(train, num_epoch=5, initializer=mx.init.Xavier(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    prefix = str(tmp_path / "sym_ckpt")
    mod.save_checkpoint(prefix, 1)

    # rebuild purely from the saved files: symbol json + params blob
    sym, arg_params, aux_params = mx.mod.load_checkpoint(prefix, 1)
    assert sym is not None, "symbol.json did not round-trip"
    mod2 = mx.mod.Module(sym, data_names=("data",),
                         label_names=("softmax_label",))
    mod2.bind(data_shapes=[("data", (24, 8))],
              label_shapes=[("softmax_label", (24,))])
    mod2.set_params(arg_params, aux_params)
    val = _toy_iter(seed=1)
    m1, m2 = mx.metric.Accuracy(), mx.metric.Accuracy()
    mod.score(val, m1)
    mod2.score(val, m2)
    assert m2.get()[1] == pytest.approx(m1.get()[1], abs=1e-6)
    assert m2.get()[1] > 0.9


@pytest.mark.skipif(len(__import__("jax").devices()) < 2,
                    reason="needs 2 devices")
def test_group2ctxs_manual_model_parallel():
    """Manual model parallel (r2 missing #6): AttrScope(ctx_group=...) +
    Module(group2ctxs=...) places each stage's compute on its own device;
    cross-device hops are tape ops so backward crosses back. Reference:
    group2ctx in Symbol.bind + example/model-parallel."""
    import jax
    with mx.AttrScope(ctx_group="stage1"):
        data = mx.sym.var("data")
        fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="g_fc1")
        act = mx.sym.Activation(fc1, act_type="relu", name="g_relu")
    with mx.AttrScope(ctx_group="stage2"):
        fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="g_fc2")
        sym = mx.sym.SoftmaxOutput(fc2, name="softmax")
    assert fc1.attr("ctx_group") == "stage1"
    assert fc2.attr("ctx_group") == "stage2"

    ctx1 = mx.context.Context("cpu", 0)
    ctx2 = mx.context.Context("cpu", 1)
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",),
                        group2ctxs={"stage1": ctx1, "stage2": ctx2})
    train = _toy_iter(seed=0)
    val = _toy_iter(seed=1)
    mod.fit(train, eval_data=val, num_epoch=10,
            initializer=mx.init.Xavier(),
            optimizer="sgd", optimizer_params={"learning_rate": 0.5})
    # the head really ran on stage2's device
    out_dev = mod.get_outputs()[0].data.devices()
    assert out_dev == {ctx2.jax_device}, out_dev
    m = mx.metric.Accuracy()
    mod.score(val, m)
    assert m.get()[1] > 0.9


def test_bucketing_module_shares_params_across_buckets():
    """Reference BucketingModule binds bucket executors with shared
    storage: training on one bucket MUST be visible in every other
    (round-4 fix: buckets previously trained private copies)."""
    def sym_gen(seq_len):
        data = mx.sym.var("data")
        fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc_shared",
                                   flatten=False)
        pooled = mx.sym.mean(fc, axis=1, name="pool")
        out = mx.sym.SoftmaxOutput(pooled, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16)
    mod.bind(data_shapes=[("data", (2, 16, 6))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})

    def batch(key):
        return mx.io.DataBatch(
            data=[nd.ones((2, key, 6))], label=[nd.zeros((2,))],
            bucket_key=key,
            provide_data=[mx.io.DataDesc("data", (2, key, 6))],
            provide_label=[mx.io.DataDesc("softmax_label", (2,))])

    mod.forward(batch(8), is_train=False)    # bucket 8 exists up front
    before = mod._buckets[8]._exec.arg_dict["fc_shared_weight"] \
        .asnumpy().copy()
    for _ in range(5):
        mod.forward_backward(batch(16))
        mod.update()
    w16 = mod._buckets[16]._exec.arg_dict["fc_shared_weight"].asnumpy()
    assert not np.allclose(w16, before)
    w8 = mod._buckets[8]._exec.arg_dict["fc_shared_weight"].asnumpy()
    np.testing.assert_array_equal(w8, w16)
    # and the other direction, optimizer state shared too
    for _ in range(2):
        mod.forward_backward(batch(8))
        mod.update()
    np.testing.assert_array_equal(
        mod._buckets[16]._exec.arg_dict["fc_shared_weight"].asnumpy(),
        mod._buckets[8]._exec.arg_dict["fc_shared_weight"].asnumpy())
    assert mod._buckets[8]._updater_states is \
        mod._buckets[16]._updater_states
