"""Overlapped input pipeline (ISSUE 2): DevicePrefetcher ordering /
StopIteration / worker-exception surfacing / clean shutdown,
AsyncDecodeIter fan-out, ImageRecordIter preprocess_threads plumbing,
thread-safe recordio random reads, the donated fused Trainer.step path,
and the DataLoader prefetch_to_device hook — all under JAX_PLATFORMS=cpu
(conftest pins the backend; speedup claims are TPU-gated, correctness is
not).
"""
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, recordio
from mxnet_tpu.gluon import nn
from mxnet_tpu.io import (AsyncDecodeIter, DataBatch, DevicePrefetcher,
                          NDArrayIter, PrefetchingIter)


def _no_prefetch_threads():
    return not any(t.name.startswith("mxtpu-device-prefetch")
                   for t in threading.enumerate())


def _wait_threads_gone(timeout=5.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if _no_prefetch_threads():
            return True
        time.sleep(0.05)
    return False


# ----------------------------------------------------------------------
# DevicePrefetcher
# ----------------------------------------------------------------------

def test_device_prefetcher_ordering_and_stop_iteration():
    src = ((np.full((4, 3), i, np.float32), np.full((4,), i, np.float32))
           for i in range(12))
    pf = DevicePrefetcher(src, depth=2)
    seen = []
    for data, label in pf:
        assert isinstance(data, mx.nd.NDArray)
        seen.append((float(data.asnumpy()[0, 0]),
                     float(label.asnumpy()[0])))
    assert seen == [(float(i), float(i)) for i in range(12)]
    # StopIteration keeps propagating and the worker is joined
    with pytest.raises(StopIteration):
        next(pf)
    assert pf._thread is None
    assert _wait_threads_gone()
    s = pf.stats.summary()
    assert s["batches"] == 12
    assert s["overlap_efficiency"] is not None
    assert 0.0 <= s["overlap_efficiency"] <= 1.0


def test_device_prefetcher_worker_exception_surfaces():
    def bad_source():
        yield np.ones((2, 2), np.float32)
        yield np.ones((2, 2), np.float32)
        raise ValueError("decode exploded")

    pf = DevicePrefetcher(bad_source(), depth=2)
    next(pf)
    next(pf)
    with pytest.raises(ValueError, match="decode exploded"):
        next(pf)
    assert pf._thread is None
    assert _wait_threads_gone()


def test_device_prefetcher_close_mid_stream_no_leaked_threads():
    def endless():
        while True:
            yield np.ones((8, 8), np.float32)

    pf = DevicePrefetcher(endless(), depth=2)
    next(pf)
    pf.close()
    assert pf._thread is None
    assert _wait_threads_gone()
    # closed prefetcher behaves as exhausted
    with pytest.raises(StopIteration):
        next(pf)


def test_device_prefetcher_mesh_sharding():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel import make_mesh, mesh_scope

    mesh = make_mesh({"dp": -1})
    dp = mesh.shape["dp"]
    batch = 2 * dp
    with mesh_scope(mesh):   # picked up implicitly, like the trainers
        pf = DevicePrefetcher(iter(
            [(np.ones((batch, 3), np.float32),
              np.zeros((batch,), np.float32))]))
        data, label = next(pf)
    assert data.data.sharding.is_equivalent_to(
        NamedSharding(mesh, P("dp", None)), 2)
    # rank-1 labels shard on axis 0 (same _eff_bax convention as the
    # fused trainers)
    assert label.data.sharding.is_equivalent_to(
        NamedSharding(mesh, P("dp")), 1)
    pf.close()


def test_device_prefetcher_databatch_structure_preserved():
    batches = [DataBatch(data=[np.ones((4, 2), np.float32)],
                         label=[np.zeros((4,), np.float32)], pad=i)
               for i in range(3)]
    pf = DevicePrefetcher(iter(batches))
    out = list(pf)
    assert [b.pad for b in out] == [0, 1, 2]
    assert all(isinstance(b, DataBatch) for b in out)
    assert all(isinstance(b.data[0], mx.nd.NDArray) for b in out)


def test_device_prefetcher_reset_replays_resettable_source():
    base = NDArrayIter(np.arange(32, dtype=np.float32).reshape(8, 4),
                       np.arange(8, dtype=np.float32), batch_size=4)
    pf = DevicePrefetcher(base, depth=2)
    assert len(list(pf)) == 2
    pf.reset()
    assert len(list(pf)) == 2
    pf.close()
    assert _wait_threads_gone()


def test_legacy_prefetching_iter_actually_prefetches():
    base = NDArrayIter(np.arange(48, dtype=np.float32).reshape(12, 4),
                       np.arange(12, dtype=np.float32), batch_size=4)
    it = PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 4)
    it.reset()
    assert len(list(it)) == 3
    it.close()
    assert _wait_threads_gone()


# ----------------------------------------------------------------------
# AsyncDecodeIter
# ----------------------------------------------------------------------

def test_async_decode_iter_in_order_batches():
    def decode(i):
        time.sleep(0.001 * (i % 3))   # jitter the completion order
        return i * 10

    it = AsyncDecodeIter(decode, range(20), batch_size=4, n_workers=4)
    assert list(it) == [[i * 10 for i in range(j, j + 4)]
                        for j in range(0, 20, 4)]
    with pytest.raises(StopIteration):
        next(it)


def test_async_decode_iter_drops_partial_batch():
    it = AsyncDecodeIter(lambda i: i, range(10), batch_size=4,
                         n_workers=2)
    assert len(list(it)) == 2    # 10 // 4, trailing 2 samples dropped


def test_async_decode_iter_exception_in_batch_order():
    def decode(i):
        if i == 6:
            raise RuntimeError("bad sample 6")
        return i

    it = AsyncDecodeIter(decode, range(12), batch_size=4, n_workers=4)
    assert next(it) == [0, 1, 2, 3]
    with pytest.raises(RuntimeError, match="bad sample 6"):
        next(it)       # the batch containing sample 6
    it.close()


def test_async_decode_iter_close_cancels_pending():
    started = []

    def decode(i):
        started.append(i)
        time.sleep(0.01)
        return i

    it = AsyncDecodeIter(decode, range(64), batch_size=4, n_workers=2,
                         lookahead=2)
    next(it)
    it.close()
    n_started = len(started)
    time.sleep(0.1)
    # nothing new scheduled after close (running samples may finish)
    assert len(started) <= n_started + 2


def test_async_decode_iter_close_joins_pool_threads():
    """ISSUE 13 satellite: close() must JOIN the decode workers, not
    just signal them — with wait=False the non-daemon pool threads were
    still winding down when the conftest 2 s thread-leak grace sampled
    them on a loaded host (the known test_real_data teardown flake)."""
    import threading

    it = AsyncDecodeIter(lambda i: i, range(32), batch_size=4,
                         n_workers=4, lookahead=2)
    next(it)
    pool_threads = list(it._pool._threads)
    assert any(t.is_alive() for t in pool_threads)
    it.close()
    # joined INSIDE close — zero grace needed, nothing for the conftest
    # leak guard to race against
    assert all(not t.is_alive() for t in pool_threads)
    assert not [t for t in threading.enumerate()
                if t.name.startswith("mxtpu-decode")]
    it.close()                                    # idempotent


def test_closing_thread_registry_prunes_dead_threads():
    """OS thread idents are reused: an ident left registered after its
    thread exited could hand the conftest leak guard's long grace to a
    LATER genuinely-leaked thread (and the registry would grow without
    bound).  closing_thread_idents() must prune exited threads."""
    from mxnet_tpu.io.prefetch import closing_thread_idents

    it = AsyncDecodeIter(lambda i: i, range(8), batch_size=4,
                         n_workers=2, lookahead=1)
    next(it)
    pool_threads = list(it._pool._threads)
    it.close()                     # registers, then joins the workers
    assert all(not t.is_alive() for t in pool_threads)
    dead_idents = {t.ident for t in pool_threads}
    assert not closing_thread_idents() & dead_idents


# ----------------------------------------------------------------------
# ImageRecordIter preprocess_threads plumbing (pure-Python decode path)
# ----------------------------------------------------------------------

def _write_rec(tmp_path, n=16, edge=32):
    import cv2
    path = str(tmp_path / "pipe.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        img = np.full((edge, edge, 3), (i * 9) % 255, np.uint8)
        _, buf = cv2.imencode(".png", img)    # lossless: exact compare
        w.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                              buf.tobytes()))
    w.close()
    return path


def test_image_record_iter_honors_preprocess_threads(tmp_path,
                                                     monkeypatch):
    from mxnet_tpu.utils import native
    monkeypatch.setattr(native, "available", lambda: False)
    path = _write_rec(tmp_path)
    it1 = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 24, 24),
                                batch_size=4, preprocess_threads=1)
    it4 = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 24, 24),
                                batch_size=4, preprocess_threads=4)
    assert it1._async_iter is None        # synchronous decode
    assert it4._async_iter is not None    # threaded fan-out is LIVE
    assert it4._async_iter._n_workers == 4
    for b1, b4 in zip(it1, it4):
        np.testing.assert_array_equal(b1.data[0].asnumpy(),
                                      b4.data[0].asnumpy())
        np.testing.assert_array_equal(b1.label[0].asnumpy(),
                                      b4.label[0].asnumpy())
    # epoch restart rebuilds the fan-out and yields the same count
    it4.reset()
    assert len(list(it4)) == 4
    it1.close()
    it4.close()


def test_image_record_iter_determinism_mode_stays_synchronous(
        tmp_path, monkeypatch):
    from mxnet_tpu import debug
    from mxnet_tpu.utils import native
    monkeypatch.setattr(native, "available", lambda: False)
    monkeypatch.setattr(debug, "determinism_enabled", lambda: True)
    path = _write_rec(tmp_path, n=8)
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 24, 24),
                               batch_size=4, preprocess_threads=4)
    assert it._async_iter is None
    assert len(list(it)) == 2


def test_recordio_read_idx_thread_safe(tmp_path):
    path = str(tmp_path / "mt")
    w = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(32):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0),
            bytes([i]) * (50 + i)))
    w.close()
    r = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "r")
    errors = []

    def hammer(tid):
        try:
            rs = np.random.RandomState(tid)
            for _ in range(100):
                k = int(rs.randint(32))
                header, payload = recordio.unpack(r.read_idx(k))
                assert float(header.label) == float(k)
                assert payload == bytes([k]) * (50 + k)
        except Exception as e:  # noqa: BLE001 — reported to main thread
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    r.close()
    assert all(f.closed for f in r._tl_handles)


# ----------------------------------------------------------------------
# fused, donated Trainer.step
# ----------------------------------------------------------------------

def _tiny_net():
    mx.random.seed(7)
    net = nn.Sequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    return net


def _train(net, optimizer, opt_kw, fused, steps=3):
    os.environ["MXTPU_FUSED_STEP"] = "1" if fused else "0"
    try:
        trainer = gluon.Trainer(net.collect_params(), optimizer, opt_kw)
        loss_fn = gluon.loss.L2Loss()
        rs = np.random.RandomState(0)
        for _ in range(steps):
            x = mx.nd.array(rs.randn(16, 10).astype("float32"))
            y = mx.nd.array(rs.randn(16, 4).astype("float32"))
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(16)
    finally:
        os.environ.pop("MXTPU_FUSED_STEP", None)
    vals = [p.data().asnumpy()
            for _, p in sorted(net.collect_params().items())]
    return vals, trainer


@pytest.mark.parametrize("optimizer,opt_kw", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("sgd", {"learning_rate": 0.1}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2, "wd": 1e-4}),
    ("adamw", {"learning_rate": 1e-2, "wd": 1e-2}),
])
def test_fused_trainer_step_matches_eager(optimizer, opt_kw):
    fused_vals, fused_tr = _train(_tiny_net(), optimizer, dict(opt_kw),
                                  fused=True)
    eager_vals, eager_tr = _train(_tiny_net(), optimizer, dict(opt_kw),
                                  fused=False)
    assert len(fused_tr._fused_jit_cache) == 1    # the jit path RAN
    assert len(eager_tr._fused_jit_cache) == 0    # ... and was off here
    for f, e in zip(fused_vals, eager_vals):
        np.testing.assert_allclose(f, e, rtol=2e-5, atol=2e-6)


def test_fused_trainer_one_program_and_counters():
    net = _tiny_net()
    vals, trainer = _train(net, "adam", {"learning_rate": 1e-3},
                           fused=True, steps=4)
    # one compiled program for the whole group, not one per param
    assert len(trainer._fused_jit_cache) == 1
    assert trainer._optimizer.num_update == 4
    # eager-format states survive for save_states/load_states
    assert all(isinstance(s, tuple) and len(s) == 2
               for s in trainer._states.values())


def test_fused_trainer_save_load_states_roundtrip(tmp_path):
    net = _tiny_net()
    _, trainer = _train(net, "adam", {"learning_rate": 1e-3}, fused=True)
    f = str(tmp_path / "states")
    trainer.save_states(f)
    net2 = _tiny_net()
    _, trainer2 = _train(net2, "adam", {"learning_rate": 1e-3},
                         fused=True)
    trainer2.load_states(f)
    assert trainer2._optimizer.num_update == trainer._optimizer.num_update
    for i, s in trainer._states.items():
        np.testing.assert_allclose(s[0].asnumpy(),
                                   trainer2._states[i][0].asnumpy())


def test_fused_trainer_falls_back_for_unsupported_optimizer():
    net = _tiny_net()
    _, trainer = _train(net, "adagrad", {"learning_rate": 0.05},
                        fused=True)
    assert len(trainer._fused_jit_cache) == 0    # eager path ran


def test_fused_trainer_stale_grad_raises():
    net = _tiny_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.array(np.ones((2, 10), np.float32))
    net(x)    # forward only — no grads
    with pytest.raises(mx.MXNetError, match="Call backward"):
        trainer.step(2)


# ----------------------------------------------------------------------
# end-to-end: decode -> DevicePrefetcher -> donated fused step
# ----------------------------------------------------------------------

def test_pipeline_end_to_end_trains(tmp_path, monkeypatch):
    from mxnet_tpu.utils import native
    monkeypatch.setattr(native, "available", lambda: False)
    path = _write_rec(tmp_path, n=16, edge=28)
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 24, 24),
                               batch_size=8, preprocess_threads=2,
                               std_r=255.0, std_g=255.0, std_b=255.0)
    net = nn.Sequential()
    net.add(nn.Flatten(), nn.Dense(16, activation="relu"))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.L2Loss()
    pf = DevicePrefetcher(it, depth=2)
    n = 0
    for batch in pf:
        data, label = batch.data[0], batch.label[0]
        with autograd.record():
            out = net(data)
            loss = loss_fn(out, mx.nd.zeros(out.shape))
        loss.backward()
        trainer.step(data.shape[0])
        n += 1
    assert n == 2
    s = pf.stats.summary()
    assert s["batches"] == 2 and s["h2d_ms_per_batch"] >= 0
    pf.close()
    it.close()
    assert _wait_threads_gone()


def test_profiler_records_pipeline_spans(tmp_path):
    from mxnet_tpu import profiler
    profiler.set_config(filename=str(tmp_path / "p.json"))
    profiler.start()
    try:
        pf = DevicePrefetcher(
            (np.ones((4, 2), np.float32) for _ in range(3)))
        list(pf)
    finally:
        profiler.stop()
    table = profiler.dumps(reset=True)
    assert "pipeline:decode" in table
    assert "pipeline:h2d" in table


def test_ndarray_iter_shuffle_cursor_restores_standalone():
    """PR 4 known gap closed (ISSUE 8 satellite): a shuffling
    NDArrayIter's mid-epoch cursor now round-trips in a FRESH process
    with an arbitrary global numpy RNG state — the saved per-epoch
    reshuffle seeds rebuild the exact order, no estimator-path RNG
    replay required."""
    data = np.arange(40, dtype=np.float32).reshape(20, 2)
    label = np.arange(20, dtype=np.float32)
    np.random.seed(0)
    it = NDArrayIter(data, label, batch_size=4, shuffle=True)
    for _ in range(5):
        it.next()                       # epoch 1 consumed
    it.reset()                          # epoch 2 reshuffles in reset()
    it.next()                           # one batch into epoch 2
    saved = it.state_dict()
    assert "shuffle_seeds" in saved and len(saved["shuffle_seeds"]) == 2
    expect = [(it.next().data[0].asnumpy(),
               it.next().label[0].asnumpy()) for _ in range(2)]

    # "fresh process": unrelated RNG history, then restore the cursor
    np.random.seed(98765)
    np.random.rand(17)
    it2 = NDArrayIter(data, label, batch_size=4, shuffle=True)
    it2.set_state(saved)
    got = [(it2.next().data[0].asnumpy(),
            it2.next().label[0].asnumpy()) for _ in range(2)]
    for (ed, el), (gd, gl) in zip(expect, got):
        np.testing.assert_array_equal(ed, gd)
        np.testing.assert_array_equal(el, gl)


def test_ndarray_iter_shuffle_same_stream_replay_still_works():
    """The estimator resume path (restore numpy RNG, re-enter the epoch
    the same way) must keep producing the identical order."""
    data = np.arange(24, dtype=np.float32).reshape(12, 2)
    np.random.seed(3)
    it = NDArrayIter(data, batch_size=4, shuffle=True)
    a = [it.next().data[0].asnumpy() for _ in range(3)]
    np.random.seed(3)
    it2 = NDArrayIter(data, batch_size=4, shuffle=True)
    b = [it2.next().data[0].asnumpy() for _ in range(3)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_image_record_iter_shuffle_cursor_restores_standalone(
        tmp_path, monkeypatch):
    """Same standalone-restore contract for the rec-file iterator: the
    saved shuffle seeds rebuild the epoch order in a fresh process."""
    from mxnet_tpu.utils import native
    monkeypatch.setattr(native, "available", lambda: False)
    path = _write_rec(tmp_path, n=16)
    np.random.seed(11)
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 24, 24),
                               batch_size=4, shuffle=True,
                               preprocess_threads=1)
    list(it)                       # epoch 1
    it.reset()                     # epoch 2 reshuffles
    it.next()
    saved = it.state_dict()
    expect = it.next()
    np.random.seed(777)            # unrelated "fresh process" RNG state
    it2 = mx.io.ImageRecordIter(path_imgrec=path,
                                data_shape=(3, 24, 24), batch_size=4,
                                shuffle=True, preprocess_threads=1)
    it2.set_state(saved)
    got = it2.next()
    np.testing.assert_array_equal(expect.label[0].asnumpy(),
                                  got.label[0].asnumpy())
    np.testing.assert_array_equal(expect.data[0].asnumpy(),
                                  got.data[0].asnumpy())
    it.close()
    it2.close()
