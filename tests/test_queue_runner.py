"""Unit tests for the TPU measurement queue runner's pure logic
(tools/tpu_queue_runner.py) — the state machine that lands the on-chip
numbers must itself be trustworthy: JSON-line parsing, state
round-trips, platform gating of the conv winner, and the knobs-file
contract bench.py consumes (bench._apply_knobs_file)."""
import json
import os

import pytest

from tools import tpu_queue_runner as qr
from tools.flash_long_seq import child_env, parse_child_line


def test_json_lines_parsing():
    text = ("garbage\n"
            '{"config": "base", "img_per_sec": 100.0}\n'
            "WARNING: noise\n"
            '{"best": {"config": "s2d"}}\n'
            "{broken json\n")
    lines = qr._json_lines(text)
    assert len(lines) == 2
    assert lines[0]["config"] == "base"
    assert "best" in lines[1]


def test_state_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setattr(qr, "QDIR", str(tmp_path))
    monkeypatch.setattr(qr, "STATE", str(tmp_path / "state.json"))
    st = qr._load_state()
    assert st == {"done": {}, "conv_results": []}
    st["done"]["conv_matrix"] = True
    st["conv_results"].append({"config": "base", "img_per_sec": 1.0})
    qr._save_state(st)
    st2 = qr._load_state()
    assert st2 == st
    # corrupt state falls back to empty, not a crash
    (tmp_path / "state.json").write_text("{broken")
    assert qr._load_state() == {"done": {}, "conv_results": []}


def test_flash_child_env_preserves_ambient_pythonpath():
    env = child_env("flash", 2048, bh=4,
                    base={"PYTHONPATH": "/ambient/site:", "OTHER": "x"})
    parts = env["PYTHONPATH"].split(os.pathsep)
    assert parts[0] == qr.REPO
    assert "/ambient/site" in parts
    assert "" not in parts          # empty component would mean cwd
    assert env["MXTPU_FLASH_IMPL"] == "flash"
    assert env["MXTPU_FLASH_L"] == "2048"
    assert env["OTHER"] == "x"


def test_parse_child_line_contract():
    assert parse_child_line("noise\nCHILD {\"impl\": \"scan\", \"L\": 8}\n")\
        == {"impl": "scan", "L": 8}
    assert parse_child_line("no child line") is None
    assert parse_child_line("CHILD {broken") is None


def test_conv_winner_knobs_contract(tmp_path, monkeypatch):
    """step_conv_matrix's knobs output must be exactly what
    bench._apply_knobs_file consumes: NCHW normalizes to null (no env
    export), s2d flag 0/1, batch passthrough."""
    import bench
    monkeypatch.setattr(qr, "QDIR", str(tmp_path))
    monkeypatch.setattr(qr, "STATE", str(tmp_path / "state.json"))
    monkeypatch.setattr(qr, "REPO", str(tmp_path))
    # simulate a completed matrix in state and run only the winner logic
    st = {"done": {}, "conv_results": [
        {"config": "base", "batch": 128, "s2d_stem": False,
         "conv_layout": "NCHW", "img_per_sec": 2000.0, "platform": "tpu"},
        {"config": "b256_s2d", "batch": 256, "s2d_stem": True,
         "conv_layout": "NCHW", "img_per_sec": 2500.0, "platform": "tpu"},
    ]}
    ok = [r for r in st["conv_results"] if "img_per_sec" in r]
    best = max(ok, key=lambda r: r["img_per_sec"])
    knobs = {"resnet_s2d": 1 if best.get("s2d_stem") else 0,
             "conv_layout": (best["conv_layout"]
                             if best.get("conv_layout") not in
                             (None, "NCHW") else None),
             "batch": best.get("batch")}
    kf = tmp_path / ".bench_knobs.json"
    kf.write_text(json.dumps(knobs))
    monkeypatch.setattr(bench, "_KNOBS", str(kf))
    for v in ("MXTPU_RESNET_S2D", "MXTPU_CONV_LAYOUT", "MXTPU_BENCH_BATCH"):
        monkeypatch.delenv(v, raising=False)
    bench._apply_knobs_file()
    assert os.environ["MXTPU_RESNET_S2D"] == "1"
    assert os.environ["MXTPU_BENCH_BATCH"] == "256"
    # NCHW stored as null -> no layout export at all
    assert "MXTPU_CONV_LAYOUT" not in os.environ
    for v in ("MXTPU_RESNET_S2D", "MXTPU_BENCH_BATCH"):
        os.environ.pop(v, None)


def test_runner_rejects_non_tpu_conv_rows():
    """The gate that keeps CPU-fallback rows out of best_conv."""
    rows = [{"config": "base", "img_per_sec": 5.0, "platform": "cpu"},
            {"config": "s2d", "img_per_sec": 2000.0, "platform": "tpu"}]
    accepted = [r for r in rows
                if "img_per_sec" in r and r.get("platform") == "tpu"]
    assert [r["config"] for r in accepted] == ["s2d"]


def test_run_child_timeout_kills_process_group(tmp_path):
    """A child that spawns its own grandchild and hangs must be fully
    reaped on timeout (group SIGTERM), with captured partial output."""
    import subprocess
    import sys
    import time
    script = tmp_path / "slow_child.py"
    script.write_text(
        "import subprocess, sys, time\n"
        "print('CHILD_STARTED', flush=True)\n"
        "grand = subprocess.Popen([sys.executable, '-c',"
        " 'import time; time.sleep(300)'])\n"
        "time.sleep(300)\n")
    t0 = time.time()
    rc, out = qr._run_child([sys.executable, str(script)],
                            dict(os.environ), timeout=3.0,
                            log_path=str(tmp_path / "log.txt"))
    took = time.time() - t0
    assert rc is None                      # timeout, not exit
    assert "CHILD_STARTED" in out          # partial output captured
    assert took < 40                       # TERM path, not a hang
    # the whole process group (incl. the grandchild) is gone
    time.sleep(0.5)
    ps = subprocess.run(["ps", "-eo", "args"], capture_output=True,
                        text=True).stdout
    assert "slow_child.py" not in ps
    assert "time.sleep(300)" not in ps


def test_run_child_normal_exit(tmp_path):
    import sys
    rc, out = qr._run_child(
        [sys.executable, "-c", "print('{\"x\": 1}')"],
        dict(os.environ), timeout=30.0,
        log_path=str(tmp_path / "log.txt"))
    assert rc == 0
    assert qr._json_lines(out) == [{"x": 1}]


def test_memory_levers_ce_smoke_and_summary():
    """memory_levers children on CPU smoke scale: fused and naive CE
    agree on the loss, and summarize() flattens results into the scalar
    dict bench.py attaches."""
    from tools.memory_levers import run_config, summarize, MATRIX
    fused = run_config("ce_fused_32k", "ce", impl="fused", vocab=32768,
                       tokens=8192)
    naive = run_config("ce_naive_32k", "ce", impl="naive", vocab=32768,
                       tokens=8192)
    assert not fused["oom"] and not naive["oom"]
    assert abs(fused["loss"] - naive["loss"]) < 0.05, (fused, naive)
    zero1 = {"config": "zero1", "kind": "zero1", "platform": "cpu",
             "param_mb": 102.2, "adam_state_mb": 204.4,
             "adam_state_mb_per_chip_zero1_dp8": 25.6,
             "adam_state_mb_per_chip_zero1_dp256": 0.8}
    s = summarize([fused, naive, zero1,
                   {"config": "ce_naive_oom32k", "kind": "ce",
                    "platform": "tpu", "oom": True, "expected_oom": True}])
    assert s["ce_fused_32k_ms"] == fused["ms_per_step"]
    assert s["ce_naive_32ktok_oom"] is True
    assert s["zero1_dp256_state_mb"] == 0.8
    assert set(MATRIX) >= {"accum_base", "ce_fused_128k", "zero1"}


def test_bench_regression_gate_wiring(tmp_path, monkeypatch):
    """The ISSUE 11 perf gate: after a TPU bench the runner diffs the
    newest BENCH_r*.json round against this run's .bench_full.json via
    tools/bench_diff.py --fail-on-regression; the non-zero exit lands
    in state and trips main()'s completion exit code."""
    real_repo = qr.REPO
    monkeypatch.setattr(qr, "REPO", str(tmp_path))
    # keep bench_diff.py reachable from the fake repo root
    os.makedirs(tmp_path / "tools")
    import shutil
    shutil.copy(os.path.join(real_repo, "tools", "bench_diff.py"),
                tmp_path / "tools" / "bench_diff.py")
    payload = {"metric": "resnet50_train_images_per_sec",
               "value": 2000.0, "unit": "img/s", "vs_baseline": 5.2,
               "platform": "tpu", "telemetry_schema_version": 1}
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump({"n": 1, "cmd": "python bench.py", "rc": 0,
                   "parsed": payload}, f)
    slow = dict(payload, value=1200.0)
    with open(tmp_path / ".bench_full.json", "w") as f:
        json.dump(slow, f)
    monkeypatch.setenv("MXTPU_BENCH_REGRESSION_PCT", "10")
    st = {}
    qr._bench_regression_gate(st)
    assert st["bench_regression"]["rc"] == 1
    assert st["bench_regression"]["verdict"]["status"] == "regression"
    # within threshold: clean
    with open(tmp_path / ".bench_full.json", "w") as f:
        json.dump(dict(payload, value=1950.0), f)
    qr._bench_regression_gate(st)
    assert st["bench_regression"]["rc"] == 0
    assert st["bench_regression"]["verdict"]["status"] == "ok"
