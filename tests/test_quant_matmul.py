"""Scaled int8/fp8 matmul training path (ISSUE 20).

THE acceptance gates:

- ``MXTPU_COMPUTE_DTYPE`` unset (or ``fp32``) is a bitwise-inert kill
  switch: ``quant_matmul(a, b)`` IS ``jnp.matmul(a, b)``;
- int8 stochastic rounding is UNBIASED (E[dequant(quant(x))] == x, the
  PR 3 wire contract now shared by the compute path);
- the custom VJP delivers gradients close to the exact ones with the
  grad-side matmuls quantized too (plain autodiff through floor would
  return zeros — the VJP is load-bearing);
- numerically fragile tags fall back to bf16 (defaults + the
  ``MXTPU_QUANT_BF16_ALLOW`` env allowlist);
- delayed scaling threads an amax history (cold start = current
  scaling; a stale scale CLIPS, visibly);
- the CONVERGENCE FLOOR: the real trainer (plain / accum / multi-step /
  ZeRO-1 — the PR 2/6 ``DataParallelTrainer`` paths) under int8 and
  fp8 compute reaches a final loss within a small margin of the fp32
  run on the same data, and the loss actually falls;
- quantized sites publish ``quant.amax.<tag>.*`` / overflow gauges.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.ops.quant_matmul import (FP8_MAX, INT8_MAX,
                                        bf16_fallback_tags,
                                        dequantize_int8,
                                        init_delayed_state,
                                        quant_matmul,
                                        quant_matmul_delayed,
                                        quantize_rtn_int8,
                                        quantize_sr_int8,
                                        resolve_compute_dtype)
from mxnet_tpu.parallel.data_parallel import DataParallelTrainer

nd = mx.nd

needs8 = pytest.mark.skipif(len(jax.devices()) < 8,
                            reason="needs 8 virtual devices")


@pytest.fixture(autouse=True)
def _unset_compute_dtype(monkeypatch):
    # every test starts from the kill-switch default; the trainer tests
    # opt in explicitly
    monkeypatch.delenv("MXTPU_COMPUTE_DTYPE", raising=False)
    monkeypatch.delenv("MXTPU_QUANT_BF16_ALLOW", raising=False)


# ----------------------------------------------------------------------
# resolution, kill switch, rounding primitives
# ----------------------------------------------------------------------

def test_resolve_and_kill_switch_bitwise(monkeypatch):
    assert resolve_compute_dtype() is None
    for off in ("", "0", "off", "fp32", "float32"):
        assert resolve_compute_dtype(off) is None
    assert resolve_compute_dtype("int8") == "int8"
    assert resolve_compute_dtype("fp8") == "fp8"
    with pytest.raises(MXNetError):
        resolve_compute_dtype("int4")
    monkeypatch.setenv("MXTPU_COMPUTE_DTYPE", "int8")
    assert resolve_compute_dtype() == "int8"
    # unset -> quant_matmul IS jnp.matmul, bitwise
    monkeypatch.delenv("MXTPU_COMPUTE_DTYPE")
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(5, 7).astype(np.float32))
    b = jnp.asarray(rng.randn(7, 3).astype(np.float32))
    np.testing.assert_array_equal(np.asarray(quant_matmul(a, b)),
                                  np.asarray(jnp.matmul(a, b)))


def test_sr_int8_is_unbiased():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(64).astype(np.float32) * 0.3)
    codes, scale = quantize_sr_int8(x, jax.random.key(0))
    assert codes.dtype == jnp.int8
    # one draw is within a quantum of x...
    assert float(jnp.max(jnp.abs(dequantize_int8(codes, scale) - x))) \
        <= float(scale) + 1e-6
    # ...and the MEAN over many draws converges on x (unbiasedness):
    # per-element SR noise is U(-q, q)-ish with q = scale, so the mean
    # of N draws sits within ~5 * scale / sqrt(N)
    keys = jax.random.split(jax.random.key(7), 512)
    deq = jax.vmap(
        lambda k: dequantize_int8(*quantize_sr_int8(x, k)))(keys)
    err = float(jnp.max(jnp.abs(jnp.mean(deq, axis=0) - x)))
    assert err <= 5.0 * float(scale) / np.sqrt(512)


def test_rtn_int8_is_the_serving_formula():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 9).astype(np.float32) * 3)
    s = jnp.float32(0.05)
    q = quantize_rtn_int8(x, s)
    ref = jnp.clip(jnp.round(x / s), -127, 127)
    assert q.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(q, np.float32),
                                  np.asarray(ref))


# ----------------------------------------------------------------------
# the quantized contraction: accuracy + custom VJP
# ----------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_quant_matmul_close_and_grads_flow(mode):
    rng = np.random.RandomState(3)
    a = jnp.asarray(rng.randn(16, 32).astype(np.float32))
    b = jnp.asarray(rng.randn(32, 8).astype(np.float32))
    exact = np.asarray(jnp.matmul(a, b))
    y = np.asarray(quant_matmul(a, b, compute_dtype=mode))
    # per-tensor 8-bit scaling: error a few percent of the output scale
    tol = 0.08 * float(np.abs(exact).max()) * (np.sqrt(32) / 4)
    assert 0.0 < float(np.abs(y - exact).max()) <= tol
    # leading dims flatten and restore
    a3 = a.reshape(4, 4, 32)
    y3 = np.asarray(quant_matmul(a3, b, compute_dtype=mode))
    assert y3.shape == (4, 4, 8)

    # custom VJP: grads close to exact, grad-side quantized, NOT zero
    # (autodiff through floor/round alone would kill the signal)
    def loss(aa, bb):
        return jnp.sum(quant_matmul(aa, bb, compute_dtype=mode) ** 2)

    da, db = jax.grad(loss, argnums=(0, 1))(a, b)
    ea, eb = jax.grad(
        lambda aa, bb: jnp.sum(jnp.matmul(aa, bb) ** 2),
        argnums=(0, 1))(a, b)
    for g, e in ((da, ea), (db, eb)):
        g, e = np.asarray(g), np.asarray(e)
        assert np.all(np.isfinite(g)) and np.abs(g).max() > 0
        assert np.abs(g - e).max() <= 0.2 * np.abs(e).max()


def test_bf16_fallback_allowlist(monkeypatch):
    rng = np.random.RandomState(4)
    a = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    b = jnp.asarray(rng.randn(16, 4).astype(np.float32))
    ref_bf16 = np.asarray(jax.lax.dot_general(
        a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32))
    assert {"head", "logits"} <= set(bf16_fallback_tags())
    y = np.asarray(quant_matmul(a, b, compute_dtype="int8", tag="head"))
    np.testing.assert_array_equal(y, ref_bf16)
    # env allowlist extends the set per call site
    monkeypatch.setenv("MXTPU_QUANT_BF16_ALLOW", "fc, router")
    assert {"fc", "router"} <= set(bf16_fallback_tags())
    y2 = np.asarray(quant_matmul(a, b, compute_dtype="int8", tag="fc"))
    np.testing.assert_array_equal(y2, ref_bf16)
    # un-listed tags stay 8-bit (SR noise: not the bf16 result)
    y3 = np.asarray(quant_matmul(a, b, compute_dtype="int8", tag="mm"))
    assert np.abs(y3 - ref_bf16).max() > 0


def test_delayed_scaling_state():
    rng = np.random.RandomState(5)
    a = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    b = jnp.asarray(rng.randn(16, 4).astype(np.float32))
    st = init_delayed_state(history=4)
    with pytest.raises(MXNetError):
        init_delayed_state(history=0)
    # kill switch: exact matmul, state untouched
    y0, st0 = quant_matmul_delayed(a, b, st)
    np.testing.assert_array_equal(np.asarray(y0),
                                  np.asarray(jnp.matmul(a, b)))
    # cold start falls back to CURRENT scaling; the history then rolls
    # this step's amax in
    y1, st1 = quant_matmul_delayed(a, b, st, compute_dtype="fp8")
    exact = np.asarray(jnp.matmul(a, b))
    assert np.abs(np.asarray(y1) - exact).max() <= 0.1 * np.abs(exact).max()
    assert float(st1["a"][0]) == pytest.approx(
        float(jnp.max(jnp.abs(a))), rel=1e-6)
    # a STALE (too small) history scale clips: feed a tensor 100x the
    # recorded amax — the quantized output must visibly saturate
    stale = {"a": st1["a"] * 0.01, "b": st1["b"] * 0.01}
    y2, _ = quant_matmul_delayed(a * 100.0, b, stale,
                                 compute_dtype="fp8")
    big_exact = exact * 100.0
    assert np.abs(np.asarray(y2) - big_exact).max() \
        > 0.5 * np.abs(big_exact).max()


def test_quant_telemetry_gauges_published():
    if not telemetry.enabled():
        pytest.skip("telemetry kill switch on")
    rng = np.random.RandomState(6)
    a = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    b = jnp.asarray(rng.randn(16, 4).astype(np.float32))
    jax.block_until_ready(quant_matmul(a, b, compute_dtype="int8",
                                       tag="probe"))
    jax.effects_barrier()
    amax = telemetry.value("quant.amax.probe.a")
    assert amax is not None and amax == pytest.approx(
        float(jnp.max(jnp.abs(a))), rel=1e-4)
    assert telemetry.value("quant.overflow_pct.probe") is not None


# ----------------------------------------------------------------------
# the convergence floor: the real trainer under 8-bit compute
# ----------------------------------------------------------------------

def _build(shard=False):
    from mxnet_tpu.gluon import block as _blk
    _blk._GLOBAL_COUNTERS.clear()
    mx.random.seed(11)
    np.random.seed(11)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(8))
    net.initialize()
    tr = DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.01}, shard_updates=shard)
    return net, tr


def _data(n=6, batch=16, din=12, classes=8, seed=0):
    rs = np.random.RandomState(seed)
    xs = [rs.randn(batch, din).astype(np.float32) for _ in range(n)]
    ys = [rs.randint(0, classes, (batch,)) for _ in range(n)]
    return xs, ys


def _losses_plain(epochs=4):
    # cycle a small FIXED batch set: random labels are memorizable, so
    # the loss trend is a real convergence signal in a handful of steps
    xs, ys = _data(4)
    _, tr = _build()
    return [float(tr.step(nd.array(x), nd.array(y)).asnumpy())
            for _ in range(epochs) for x, y in zip(xs, ys)]


@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_trainer_convergence_floor(mode, monkeypatch):
    """The tentpole gate: the SAME plain-trainer run under 8-bit
    compute (env read at trace time, so the whole jitted step routes
    FullyConnected through quant_matmul) must fall and land within a
    small margin of the fp32 final loss."""
    ref = _losses_plain()
    monkeypatch.setenv("MXTPU_COMPUTE_DTYPE", mode)
    got = _losses_plain()
    assert all(np.isfinite(got))
    assert got[-1] < got[0]                       # it trains
    assert abs(got[-1] - ref[-1]) <= 0.15         # the floor
    # the route is LIVE: an 8-bit forward is not the f32 forward
    # (losses may agree to print precision on this toy problem, so
    # probe the op seam directly)
    x = nd.array(np.random.RandomState(1).randn(4, 12).astype(np.float32))
    w = nd.array(np.random.RandomState(2).randn(8, 12).astype(np.float32))
    q = nd.FullyConnected(x, w, no_bias=True, num_hidden=8).asnumpy()
    monkeypatch.delenv("MXTPU_COMPUTE_DTYPE")
    f = nd.FullyConnected(x, w, no_bias=True, num_hidden=8).asnumpy()
    assert np.abs(q - f).max() > 0


@pytest.mark.slow   # accum/multi-step twins of the convergence floor
# (same quant seam, extra trainer graphs); plain + ZeRO-1 stay tier-1
def test_trainer_accum_and_multi_step_int8(monkeypatch):
    """The composed paths (PR 6): microbatch accumulation and K-steps-
    in-one-program both run under int8 compute, stay finite, and fall."""
    monkeypatch.setenv("MXTPU_COMPUTE_DTYPE", "int8")
    xs, ys = _data(4)
    _, tr = _build()
    l_acc = [float(tr.step_accum(nd.array(x), nd.array(y),
                                 n_micro=2).asnumpy())
             for _ in range(3) for x, y in zip(xs, ys)]
    assert all(np.isfinite(l_acc)) and l_acc[-1] < l_acc[0]
    _, tr2 = _build()
    out = []
    for _ in range(3):
        for i in range(0, 4, 2):
            got = tr2.step_multi([(nd.array(xs[j]), nd.array(ys[j]))
                                  for j in range(i, i + 2)])
            out += list(np.asarray(got.asnumpy()).ravel())
    assert all(np.isfinite(out)) and out[-1] < out[0]


@needs8
def test_trainer_zero1_int8(monkeypatch):
    """ZeRO-1 (shard_updates): the quantized step composes with the
    sharded optimizer path on the 8-device CPU mesh."""
    monkeypatch.setenv("MXTPU_COMPUTE_DTYPE", "int8")
    xs, ys = _data(4)
    _, tr = _build(shard=True)
    ls = [float(tr.step(nd.array(x), nd.array(y)).asnumpy())
          for _ in range(3) for x, y in zip(xs, ys)]
    assert all(np.isfinite(ls)) and ls[-1] < ls[0]
