"""Contrib + auxiliary subsystems: contrib.rnn cells, ImageDetIter,
Estimator, profiler, exception propagation, visualization.
(reference: tests/python/unittest/{test_contrib_*,test_profiler,
test_exc_handling}.py)"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _tape, autograd, gluon, image

nd = mx.nd


def test_variational_dropout_mask_constant_over_time():
    from mxnet_tpu.gluon.contrib.rnn import VariationalDropoutCell
    from mxnet_tpu.gluon.rnn.rnn_cell import RNNCell
    base = RNNCell(6)
    cell = VariationalDropoutCell(base, drop_inputs=0.5)
    cell.initialize()
    prev = _tape.set_training(True)
    try:
        x = nd.ones((2, 4))
        cell(x, cell.begin_state(batch_size=2))
        mask1 = cell._mask_inputs.asnumpy()
        cell(x, cell.begin_state(batch_size=2))
        mask2 = cell._mask_inputs.asnumpy()
    finally:
        _tape.set_training(prev)
    np.testing.assert_array_equal(mask1, mask2)    # same mask until reset
    cell.reset()
    assert cell._mask_inputs is None


def test_conv2d_lstm_cell_shapes():
    from mxnet_tpu.gluon.contrib.rnn import Conv2DLSTMCell
    cell = Conv2DLSTMCell((3, 8, 8), hidden_channels=5)
    cell.initialize()
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(nd.random.uniform(shape=(2, 3, 8, 8)), states)
    assert out.shape == (2, 5, 8, 8)
    assert new_states[0].shape == (2, 5, 8, 8)
    assert new_states[1].shape == (2, 5, 8, 8)


def test_image_det_iter_flip_adjusts_boxes():
    data = np.zeros((2, 8, 8, 3), np.float32)
    label = np.array([[[1.0, 0.1, 0.2, 0.4, 0.6]]] * 2, np.float32)
    it = image.ImageDetIter(
        2, (3, 8, 8), data=data, label=label,
        aug_list=[image.DetHorizontalFlipAug(p=1.0)])
    batch = next(it)
    out = batch.label[0].asnumpy()[0, 0]
    np.testing.assert_allclose(out[[1, 3]], [0.6, 0.9], atol=1e-6)
    np.testing.assert_allclose(out[[2, 4]], [0.2, 0.6], atol=1e-6)


def test_estimator_fit_and_early_stop():
    from mxnet_tpu.gluon.contrib.estimator import Estimator, TrainEnd

    class Flag(TrainEnd):
        called = False

        def train_end(self, estimator):
            Flag.called = True

    net = gluon.nn.Dense(3)
    net.initialize()
    data = [(nd.random.uniform(shape=(8, 6)),
             nd.array(np.random.RandomState(0).randint(0, 3, 8)))
            for _ in range(3)]
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss())
    est.fit(data, epochs=2, event_handlers=[Flag()])
    assert est.current_epoch == 2
    assert Flag.called


def test_profiler_scoped_events(tmp_path):
    from mxnet_tpu import profiler
    f = str(tmp_path / "trace.json")
    profiler.set_config(profile_all=True, filename=f)
    profiler.start()
    domain = profiler.Domain("unit")
    with domain.new_task("unit_task"):
        nd.dot(nd.ones((8, 8)), nd.ones((8, 8))).wait_to_read()
    profiler.stop()
    out = profiler.dumps()
    assert "unit_task" in out or os.path.exists(f)


def test_exception_propagation_clear_message():
    with pytest.raises(mx.MXNetError):
        nd.reshape(nd.zeros((2, 2)), (-5,))       # invalid reshape code
    with pytest.raises(mx.MXNetError):
        nd.reshape(nd.zeros((2, 2)), (-3, -3))    # -3 past the input rank
    with pytest.raises(mx.MXNetError):
        gluon.nn.Dense(4).weight.data()      # uninitialized param
    # shape errors from jax surface as exceptions, not hangs
    with pytest.raises(Exception):
        nd.dot(nd.ones((2, 3)), nd.ones((2, 3)))


def test_visualization_print_summary():
    sym = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4,
                                name="fc")
    out = mx.viz.print_summary(mx.sym.SoftmaxOutput(sym, name="softmax"),
                               shape={"data": (1, 8)})
    assert out is None or isinstance(out, str)


def test_npx_and_np_namespaces():
    assert mx.np.arange(3).shape == (3,)
    from mxnet_tpu import npx
    assert hasattr(npx, "set_np") or hasattr(npx, "waitall") or True


def test_runtime_features():
    feats = mx.runtime.Features()
    names = [str(f) for f in feats] if hasattr(feats, "__iter__") else \
        dir(feats)
    assert names


def test_async_checkpointer_roundtrip(tmp_path):
    """Async save -> wait -> load must round-trip; training-side mutation
    after save() must NOT leak into the snapshot (SURVEY.md §5.4
    orbax-style async checkpoint)."""
    import numpy as np
    from mxnet_tpu.checkpoint import AsyncCheckpointer
    from mxnet_tpu.ndarray import utils as nd_utils

    w = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = nd.array(np.ones(3, dtype=np.float32))
    ckpt = AsyncCheckpointer()
    path = str(tmp_path / "m.params")
    ticket = ckpt.save(path, {"w": w, "b": b})
    # mutate the HANDLE after save: jax arrays are immutable, so the
    # snapshot must still hold the old values
    w += 100.0
    assert ticket.wait(30) == path
    loaded = nd_utils.load(path)
    np.testing.assert_allclose(loaded["w"].asnumpy(),
                               np.arange(6).reshape(2, 3))
    np.testing.assert_allclose(loaded["b"].asnumpy(), np.ones(3))
    # second save joins the first; errors surface on wait
    t2 = ckpt.save(path, {"w": w})
    ckpt.wait_until_finished()
    np.testing.assert_allclose(nd_utils.load(path)["w"].asnumpy(),
                               np.arange(6).reshape(2, 3) + 100.0)


def test_contrib_round3_tail():
    """boolean_mask/index_copy/index_array/allclose/gradientmultiplier/
    fft+ifft/count_sketch (reference src/operator/contrib/)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    c = nd.contrib
    d = nd.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    out = c.boolean_mask(d, nd.array([1, 0, 1]))
    assert out.asnumpy().tolist() == [[1, 2], [5, 6]]
    d.attach_grad()
    with autograd.record():
        loss = c.boolean_mask(d, nd.array([1, 0, 1])).sum()
    loss.backward()
    assert d.grad.asnumpy().tolist() == [[1, 1], [0, 0], [1, 1]]

    out = c.index_copy(nd.zeros((4, 2)), nd.array([1, 3]), nd.ones((2, 2)))
    np.testing.assert_allclose(out.asnumpy(),
                               [[0, 0], [1, 1], [0, 0], [1, 1]])

    ia = c.index_array(nd.zeros((2, 3)))
    assert ia.shape == (2, 3, 2) and ia.asnumpy()[1, 2].tolist() == [1, 2]
    assert c.index_array(nd.zeros((2, 3)), axes=(1,)).shape == (2, 3, 1)

    assert float(c.allclose(nd.ones((3,)), nd.ones((3,))).asnumpy()) == 1.0
    assert float(c.allclose(nd.ones((3,)), nd.zeros((3,))).asnumpy()) == 0.0

    # gradient reversal: forward identity, grad scaled by the scalar
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = c.gradientmultiplier(x, scalar=-0.5).sum()
    y.backward()
    assert float(y.asnumpy()) == 2.0
    assert x.grad.asnumpy()[0] == -0.5

    # fft/ifft roundtrip with the reference's interleaved layout + n scale
    sig = nd.array(np.random.RandomState(0).randn(2, 8).astype(np.float32))
    F = c.fft(sig)
    assert F.shape == (2, 16)
    rec = c.ifft(F) / 8
    np.testing.assert_allclose(rec.asnumpy(), sig.asnumpy(), atol=1e-5)

    # count sketch preserves inner products in expectation; check exact
    # scatter on a tiny case: h=[0,0], s=[1,-1], x=[3,5] -> out[0]=-2
    cs = c.count_sketch(nd.array([[3.0, 5.0]]), nd.array([0, 0]),
                        nd.array([1.0, -1.0]), out_dim=2)
    np.testing.assert_allclose(cs.asnumpy(), [[-2.0, 0.0]])


def test_estimator_checkpoint_and_early_stopping(tmp_path):
    """CheckpointHandler (rotation + best) and EarlyStoppingHandler
    (reference gluon/contrib/estimator/event_handler.py)."""
    import os

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib import estimator as est

    net = nn.Dense(2, in_units=4)
    net.initialize()
    data = [(nd.ones((8, 4)), nd.zeros((8,)))]
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    acc = mx.metric.Accuracy()
    e = est.Estimator(net, loss, train_metrics=[acc])
    ckpt = est.CheckpointHandler(str(tmp_path), monitor=acc,
                                 save_best=True, mode="max",
                                 max_checkpoints=2)
    e.fit(data, epochs=4, event_handlers=[ckpt])
    files = sorted(os.listdir(str(tmp_path)))
    # rotation keeps 2 epoch files + the best file
    assert sum("epoch" in f for f in files) == 2, files
    assert any("best" in f for f in files)

    # early stopping: constant metric -> no improvement -> stops after
    # patience epochs, well before the epoch cap
    acc2 = mx.metric.Accuracy()
    e2 = est.Estimator(net, loss, train_metrics=[acc2])
    stop = est.EarlyStoppingHandler(acc2, mode="max", patience=2)
    e2.fit(data, epochs=50, event_handlers=[stop])
    assert e2.current_epoch <= 5

    # validation handler runs the eval_fn per period
    seen = []
    vh = est.ValidationHandler([1], eval_fn=lambda d: seen.append(1),
                               epoch_period=2)
    e3 = est.Estimator(net, loss, train_metrics=[mx.metric.Accuracy()])
    e3.fit(data, epochs=4, event_handlers=[vh])
    assert len(seen) == 2
