"""Blocked fused linear+CE (mxnet_tpu/ops/blocked_cross_entropy.py):
numerics vs materialized-logit CE, grads via autograd and jax, padding
and block-size edge cases.  The memory claim is structural (lax.scan
over vocab blocks — the (N, V) logit tensor is absent from the jaxpr)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.ops import fused_linear_cross_entropy


def _naive(x, w, t):
    logits = x @ w
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    return lse - jnp.take_along_axis(logits, t[:, None], 1)[:, 0]


@pytest.mark.parametrize("block", [64, 128, 4096])
def test_blocked_ce_matches_naive(block):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(12, 24).astype(np.float32))
    w = jnp.asarray(rng.randn(24, 500).astype(np.float32) * 0.1)
    t = jnp.asarray(rng.randint(0, 500, (12,)))
    np.testing.assert_allclose(
        np.asarray(fused_linear_cross_entropy(x, w, t, block=block)),
        np.asarray(_naive(x, w, t)), rtol=1e-5, atol=1e-5)


def test_blocked_ce_grads_match_naive():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 16).astype(np.float32))
    w = jnp.asarray(rng.randn(16, 300).astype(np.float32) * 0.1)
    t = jnp.asarray(rng.randint(0, 300, (8,)))
    gr = jax.grad(lambda a, b: _naive(a, b, t).mean(), (0, 1))(x, w)
    gf = jax.grad(lambda a, b: fused_linear_cross_entropy(
        a, b, t, block=64).mean(), (0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gf[0]), np.asarray(gr[0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gr[1]),
                               rtol=1e-5, atol=1e-6)


def test_blocked_ce_no_full_logits_in_jaxpr():
    """Structural memory proof: no (N, V)-shaped intermediate is created
    anywhere in the traced forward."""
    rng = np.random.RandomState(2)
    N, d, V = 4, 8, 50000
    x = jnp.asarray(rng.randn(N, d).astype(np.float32))
    w = jnp.asarray(rng.randn(d, V).astype(np.float32) * 0.1)
    t = jnp.asarray(rng.randint(0, V, (N,)))
    jaxpr = jax.make_jaxpr(
        lambda a, b, c: fused_linear_cross_entropy(a, b, c, block=1024))(
        x, w, t)
    for eqn in jaxpr.jaxpr.eqns:
        for v in eqn.outvars:
            shape = getattr(getattr(v, "aval", None), "shape", ())
            assert tuple(shape) != (N, V), f"full logits appear: {eqn}"


def test_blocked_ce_ndarray_contrib_and_autograd():
    rng = np.random.RandomState(3)
    x = nd.array(rng.randn(6, 12).astype(np.float32))
    w = nd.array(rng.randn(12, 200).astype(np.float32) * 0.1)
    t = nd.array(rng.randint(0, 200, (6,)).astype(np.float32))
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        loss = nd.contrib.fused_linear_cross_entropy(x, w, t, block=64)
        loss.mean().backward()
    gr = jax.grad(lambda a, b: _naive(a, b, jnp.asarray(
        t.asnumpy(), jnp.int32)).mean(), (0, 1))(
        jnp.asarray(x.asnumpy()), jnp.asarray(w.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), np.asarray(gr[0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w.grad.asnumpy(), np.asarray(gr[1]),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_llama_fused_ce_loss_matches_logits_path():
    from mxnet_tpu.gluon.model_zoo.nlp.llama import llama_tiny
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    net = llama_tiny()
    net.initialize()
    rng = np.random.RandomState(0)
    tokens = nd.array(rng.randint(0, 256, (2, 16)))
    targets = nd.array(rng.randint(0, 256, (2, 16)))
    logits = net(tokens)
    ref = SoftmaxCrossEntropyLoss(axis=-1, batch_axis=0)(
        logits.reshape((-1, logits.shape[-1])),
        targets.reshape((-1,)))
    fused = net.fused_ce_loss(tokens, targets, block=64)
    np.testing.assert_allclose(fused.asnumpy().reshape(-1).mean(),
                               ref.asnumpy().mean(), rtol=1e-4)
    # grads flow through the fused path and training steps reduce loss
    from mxnet_tpu import gluon
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    losses = []
    for _ in range(5):
        with autograd.record():
            loss = net.fused_ce_loss(tokens, targets, block=64).mean()
        loss.backward()
        tr.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0]


def test_blocked_ce_ignore_index_and_out_of_range():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(6, 8).astype(np.float32))
    w = jnp.asarray(rng.randn(8, 100).astype(np.float32) * 0.1)
    t = jnp.asarray(np.array([5, -1, 99, 100, 7, -100]))
    loss = fused_linear_cross_entropy(x, w, t, block=32)
    # -1 / -100 / 100 (==V) are padding: zero loss, zero grad
    assert float(loss[1]) == 0.0 and float(loss[5]) == 0.0
    assert float(loss[3]) == 0.0
    assert float(loss[0]) > 0.0 and float(loss[2]) > 0.0
    gx = jax.grad(lambda a: fused_linear_cross_entropy(
        a, w, t, block=32).sum())(x)
    np.testing.assert_array_equal(np.asarray(gx[1]), 0.0)
    np.testing.assert_array_equal(np.asarray(gx[3]), 0.0)
    assert np.abs(np.asarray(gx[0])).sum() > 0
    # explicit ignore_index masks an otherwise-valid label
    loss2 = fused_linear_cross_entropy(x, w, t, block=32, ignore_index=5)
    assert float(loss2[0]) == 0.0


def test_blocked_ce_bf16_weight_not_upcast_whole():
    """The head weight must enter the scan in its own dtype (per-block
    f32 cast); a full-size f32 copy of w would double HBM for bf16
    heads.  Structural check: no (d, Vpad)-shaped f32 tensor in the
    traced forward."""
    rng = np.random.RandomState(5)
    N, d, V, block = 4, 16, 4096, 512
    x = jnp.asarray(rng.randn(N, d).astype(np.float32))
    w = jnp.asarray(rng.randn(d, V).astype(np.float32)).astype(jnp.bfloat16)
    t = jnp.asarray(rng.randint(0, V, (N,)))

    def walk(jaxpr, bad):
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if aval is not None and tuple(aval.shape) == (d, V) and \
                        aval.dtype == jnp.float32:
                    bad.append(eqn)
            for sub in jax.core.jaxprs_in_params(eqn.params) \
                    if hasattr(jax.core, "jaxprs_in_params") else []:
                walk(sub, bad)
        return bad

    jaxpr = jax.make_jaxpr(lambda a, b, c: fused_linear_cross_entropy(
        a, b, c, block=block))(x, w, t)
    assert not walk(jaxpr.jaxpr, []), "full f32 copy of the head weight"
    # numerics still match at bf16-weight precision
    ref = _naive(x, w.astype(jnp.float32), t)
    got = fused_linear_cross_entropy(x, w, t, block=block)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_blocked_ce_backward_never_materializes_logits():
    """The (N, V) logit tensor must be absent from the DIFFERENTIATED
    trace too (the backward recomputes block softmax), recursing into
    scan/custom_vjp sub-jaxprs."""
    rng = np.random.RandomState(6)
    N, d, V = 4, 8, 50000
    x = jnp.asarray(rng.randn(N, d).astype(np.float32))
    w = jnp.asarray(rng.randn(d, V).astype(np.float32) * 0.1)
    t = jnp.asarray(rng.randint(0, V, (N,)))

    def walk(jaxpr, bad):
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if aval is not None and tuple(aval.shape) == (N, V):
                    bad.append(str(eqn)[:120])
            for val in eqn.params.values():
                for sub in _subjaxprs(val):
                    walk(sub, bad)
        return bad

    def _subjaxprs(val):
        out = []
        vals = val if isinstance(val, (list, tuple)) else [val]
        for v in vals:
            core = getattr(v, "jaxpr", None)
            if core is not None:
                out.append(core if hasattr(core, "eqns") else v.jaxpr)
        return out

    jaxpr = jax.make_jaxpr(jax.grad(
        lambda a, b: fused_linear_cross_entropy(a, b, t, block=1024)
        .mean(), argnums=(0, 1)))(x, w)
    bad = walk(jaxpr.jaxpr, [])
    assert not bad, f"full logits in backward: {bad}"


@pytest.mark.slow
def test_llama_fused_ce_loss_tied_embeddings():
    """Tied head: the embedding weight takes grads from BOTH the lookup
    and the fused CE head; training must still descend."""
    from mxnet_tpu.gluon.model_zoo.nlp.llama import llama_tiny
    from mxnet_tpu import gluon
    net = llama_tiny(tie_embeddings=True)
    net.initialize()
    rng = np.random.RandomState(7)
    tokens = nd.array(rng.randint(0, 256, (2, 12)))
    targets = nd.array(rng.randint(0, 256, (2, 12)))
    # parity with the logits path
    logits = net(tokens)
    ref = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1, batch_axis=0)(
        logits.reshape((-1, logits.shape[-1])), targets.reshape((-1,)))
    fused = net.fused_ce_loss(tokens, targets, block=64)
    np.testing.assert_allclose(fused.asnumpy().mean(),
                               ref.asnumpy().mean(), rtol=1e-4)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    losses = []
    for _ in range(5):
        with autograd.record():
            loss = net.fused_ce_loss(tokens, targets, block=64).mean()
        loss.backward()
        tr.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0]
