"""Round-4 op-registry tail, second batch: the remaining sample_*
distributions (negative binomial family), fused mixed-precision
multi-tensor SGD, legacy utility ops, and the RPN proposal contrib ops.
Reference: src/operator/random/multisample_op.cc, optimizer_op.cc
(multi_mp_sgd*), contrib/reset_arrays.cc, ndarray_function.cc
(OnehotEncode), contrib/proposal.cc, contrib/multi_proposal.cc,
contrib/quadratic_op.cc, contrib/transformer.cc (div_sqrt_dim),
contrib/dgl_graph.cc (EdgeID)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_sample_negative_binomial_moments():
    mx.random.seed(7)
    k = nd.array([10.0, 50.0])
    p = nd.array([0.5, 0.2])
    s = nd.sample_negative_binomial(k, p, shape=40000).asnumpy()
    # mean k(1-p)/p, var k(1-p)/p^2
    np.testing.assert_allclose(s.mean(axis=1), [10.0, 200.0], rtol=0.05)
    np.testing.assert_allclose(s.var(axis=1), [20.0, 1000.0], rtol=0.1)
    assert (s >= 0).all() and np.allclose(s, np.round(s))


def test_sample_generalized_negative_binomial_moments():
    mx.random.seed(11)
    mu = nd.array([4.0, 9.0])
    alpha = nd.array([0.25, 0.1])
    s = nd.sample_generalized_negative_binomial(
        mu, alpha, shape=40000).asnumpy()
    np.testing.assert_allclose(s.mean(axis=1), [4.0, 9.0], rtol=0.05)
    # var = mu + alpha * mu^2
    np.testing.assert_allclose(s.var(axis=1), [8.0, 17.1], rtol=0.1)


def test_random_negative_binomial_namespace():
    mx.random.seed(5)
    s = nd.random.negative_binomial(k=20, p=0.4, shape=(20000,)).asnumpy()
    np.testing.assert_allclose(s.mean(), 20 * 0.6 / 0.4, rtol=0.05)
    g = nd.random.generalized_negative_binomial(
        mu=3.0, alpha=0.5, shape=(20000,)).asnumpy()
    np.testing.assert_allclose(g.mean(), 3.0, rtol=0.05)
    np.testing.assert_allclose(g.var(), 3.0 + 0.5 * 9.0, rtol=0.12)


def test_multi_mp_sgd_update_matches_fp32_master():
    w = nd.array(np.ones(6), dtype="float16")
    g = nd.array(np.full(6, 0.5), dtype="float16")
    w32 = nd.array(np.ones(6), dtype="float32")
    nd.multi_mp_sgd_update(w, g, w32, lrs=[0.1], wds=[0.01])
    expect32 = 1.0 - 0.1 * (0.5 + 0.01 * 1.0)
    np.testing.assert_allclose(w32.asnumpy(), expect32, rtol=1e-6)
    np.testing.assert_allclose(w.asnumpy(), expect32, rtol=1e-3)
    assert w.dtype == np.float16 and w32.dtype == np.float32


def test_multi_mp_sgd_mom_update_two_groups():
    ws = [nd.array(np.ones(4), dtype="float16") for _ in range(2)]
    gs = [nd.array(np.full(4, 1.0), dtype="float16") for _ in range(2)]
    ms = [nd.zeros((4,)) for _ in range(2)]
    w32s = [nd.array(np.ones(4), dtype="float32") for _ in range(2)]
    arrays = []
    for i in range(2):
        arrays += [ws[i], gs[i], ms[i], w32s[i]]
    nd.multi_mp_sgd_mom_update(*arrays, lrs=[0.1, 0.2], wds=[0.0, 0.0],
                               momentum=0.9)
    # step 1: m = -lr*g; w32 += m
    np.testing.assert_allclose(ms[0].asnumpy(), -0.1, rtol=1e-6)
    np.testing.assert_allclose(w32s[1].asnumpy(), 0.8, rtol=1e-6)
    np.testing.assert_allclose(ws[1].asnumpy(), 0.8, rtol=1e-3)


def test_reset_arrays_zeroes_in_place():
    a = nd.array(np.arange(6.0))
    b = nd.ones((2, 3))
    nd.reset_arrays(a, b, num_arrays=2)
    assert (a.asnumpy() == 0).all() and (b.asnumpy() == 0).all()
    with pytest.raises(mx.MXNetError):
        nd.reset_arrays(a, num_arrays=3)


def test_one_hot_encode_legacy():
    idx = nd.array([0.0, 2.0, 1.0])
    out = nd.zeros((3, 4))
    ret = nd.one_hot_encode(idx, out)
    expect = np.eye(4)[[0, 2, 1]]
    np.testing.assert_array_equal(out.asnumpy(), expect)
    assert ret is out
    assert nd.onehot_encode is nd.one_hot_encode


def test_contrib_quadratic_and_div_sqrt_dim():
    x = nd.array(np.random.RandomState(0).randn(3, 8).astype(np.float32))
    q = nd.contrib.quadratic(x, a=2.0, b=-1.0, c=0.5).asnumpy()
    np.testing.assert_allclose(
        q, 2 * x.asnumpy() ** 2 - x.asnumpy() + 0.5, rtol=1e-6)
    d = nd.contrib.div_sqrt_dim(x).asnumpy()
    np.testing.assert_allclose(d, x.asnumpy() / np.sqrt(8.0), rtol=1e-6)


def test_contrib_edge_id_csr():
    import mxnet_tpu.ndarray.sparse as sp
    dense = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 4]], dtype=np.float32)
    csr = sp.csr_matrix(dense)
    eid = nd.contrib.edge_id(csr, nd.array([0, 1, 1, 2, 0]),
                             nd.array([1, 0, 2, 2, 0]))
    np.testing.assert_array_equal(eid.asnumpy(), [0, 1, 2, 3, -1])


def _proposal_inputs(B, A, H, W, seed=0):
    rng = np.random.RandomState(seed)
    cls = nd.array(rng.rand(B, 2 * A, H, W).astype(np.float32))
    bbox = nd.array(((rng.rand(B, 4 * A, H, W) - 0.5) * 0.2)
                    .astype(np.float32))
    info = nd.array(np.tile([64.0, 64.0, 1.0], (B, 1)).astype(np.float32))
    return cls, bbox, info


def test_multi_proposal_shapes_and_validity():
    cls, bbox, info = _proposal_inputs(2, 12, 4, 4)
    rois, scores = nd.contrib.MultiProposal(
        cls, bbox, info, rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10,
        output_score=True)
    r, s = rois.asnumpy(), scores.asnumpy()
    assert r.shape == (20, 5) and s.shape == (20, 1)
    # batch index column, box validity, image clipping
    assert set(r[:10, 0]) == {0.0} and set(r[10:, 0]) == {1.0}
    assert (r[:, 1:3] <= r[:, 3:5]).all()
    assert (r[:, 1:] >= 0).all() and (r[:, 1:] <= 63).all()


def test_multi_proposal_nms_suppresses_overlaps():
    # duplicate score maps across anchors -> heavy overlap; NMS must keep
    # far fewer than pre_nms boxes at a tight threshold
    cls, bbox, info = _proposal_inputs(1, 12, 6, 6, seed=3)
    rois = nd.contrib.MultiProposal(
        cls, bbox, info, rpn_pre_nms_top_n=100, rpn_post_nms_top_n=40,
        threshold=0.5).asnumpy()
    boxes = rois[:, 1:]
    nonzero = boxes[(boxes != 0).any(axis=1)]
    # pairwise IoU among survivors stays under the threshold
    x1, y1, x2, y2 = nonzero.T
    area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    for i in range(len(nonzero)):
        for j in range(i + 1, len(nonzero)):
            xx1, yy1 = max(x1[i], x1[j]), max(y1[i], y1[j])
            xx2, yy2 = min(x2[i], x2[j]), min(y2[i], y2[j])
            inter = max(xx2 - xx1, 0) * max(yy2 - yy1, 0)
            iou = inter / max(area[i] + area[j] - inter, 1e-9)
            assert iou <= 0.5 + 1e-5


def test_proposal_single_image_and_batch_guard():
    cls, bbox, info = _proposal_inputs(1, 12, 4, 4)
    r = nd.contrib.Proposal(cls, bbox, info, rpn_pre_nms_top_n=30,
                            rpn_post_nms_top_n=8)
    assert r.shape == (8, 5)
    cls2, bbox2, info2 = _proposal_inputs(2, 12, 4, 4)
    with pytest.raises(mx.MXNetError):
        nd.contrib.Proposal(cls2, bbox2, info2)
    with pytest.raises(mx.MXNetError):
        nd.contrib.MultiProposal(cls, bbox, info, iou_loss=True)


def test_multi_sgd_clip_sentinel_and_num_weights():
    # clip_gradient=-1.0 is the reference's no-clip sentinel, NOT a bound
    w = nd.array(np.ones(4)); g = nd.array(np.full(4, 0.5))
    w32 = nd.array(np.ones(4))
    nd.multi_mp_sgd_update(w, g, w32, lrs=[0.1], wds=[0.0],
                           clip_gradient=-1.0, num_weights=1)
    np.testing.assert_allclose(w32.asnumpy(), 0.95, rtol=1e-6)
    w2 = nd.array(np.ones(4)); g2 = nd.array(np.full(4, 0.5))
    nd.multi_sgd_update(w2, g2, lrs=[0.1], wds=[0.0], clip_gradient=-1.0,
                        num_weights=1)
    np.testing.assert_allclose(w2.asnumpy(), 0.95, rtol=1e-6)
    with pytest.raises(mx.MXNetError):
        nd.multi_sgd_update(w2, g2, lrs=[0.1], wds=[0.0], num_weights=3)


def test_one_hot_encode_shape_mismatch_raises():
    with pytest.raises(mx.MXNetError):
        nd.one_hot_encode(nd.array([0.0, 1.0]), nd.zeros((5, 3)))


def test_proposal_nms_plus_one_convention():
    # 1-pixel boxes (x1==x2) have area 1 in the +1 convention; exact
    # duplicates of them must suppress each other, not pass NMS with IoU 0
    from mxnet_tpu.ndarray.contrib import _proposal_one
    import jax.numpy as jnp
    anchors = jnp.asarray([[0.0, 0.0, 0.0, 0.0]] * 2)   # two 1-px anchors
    scores = jnp.ones((2, 1, 1))
    deltas = jnp.zeros((8, 1, 1))
    boxes, scores_out = _proposal_one(
        scores, deltas, jnp.asarray([8.0, 8.0, 1.0]), anchors, 1.0,
        pre_nms=2, post_nms=2, thresh=0.5, min_size=1)
    kept = np.asarray(scores_out) > 0
    assert kept.sum() == 1   # the duplicate was suppressed
