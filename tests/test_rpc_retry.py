"""RPC retry/backoff policy + typed transport errors (ISSUE 19).

The whole policy is gated under FakeClock with ZERO real sleeps: the
injectable ``now``/``sleep`` seams exist exactly so tier-1 can assert
deadlines, deterministic seeded backoff, retry telemetry and the final
flight dump without waiting out a single real timeout.
"""
import socket
import threading

import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.kvstore.rpc import (PeerUnreachable, RetryPolicy,
                                   RPCError, RPCTimeout, classify)
from mxnet_tpu.testing.faults import FakeClock


def _policy(clock, **kw):
    """A policy whose sleeps advance the FakeClock instead of blocking."""
    kw.setdefault("now", clock)
    kw.setdefault("sleep", clock.advance)
    return RetryPolicy(**kw)


def _counters():
    return telemetry.snapshot().get("counters", {})


# ----------------------------------------------------------------------
# typed wrapping
# ----------------------------------------------------------------------

def test_classify_wraps_raw_errors_with_peer_and_op():
    e = classify(ConnectionRefusedError("refused"), peer="h:1",
                 op="pull", attempts=3)
    assert isinstance(e, PeerUnreachable)
    assert isinstance(e, ConnectionError)   # pre-19 guards keep working
    assert e.peer == "h:1" and e.op == "pull" and e.attempts == 3
    assert "pull" in str(e) and "h:1" in str(e)

    t = classify(socket.timeout("slow"), peer="h:2", op="push")
    assert isinstance(t, RPCTimeout)
    assert t.peer == "h:2" and t.op == "push"


def test_classify_passes_through_already_typed():
    orig = RPCTimeout("x", peer="p", op="barrier")
    assert classify(orig, peer="other") is orig


# ----------------------------------------------------------------------
# backoff: bounded, exponential, deterministic under a seed
# ----------------------------------------------------------------------

def test_backoff_deterministic_and_bounded():
    a = RetryPolicy(backoff_s=0.1, backoff_max_s=0.5, seed=7)
    b = RetryPolicy(backoff_s=0.1, backoff_max_s=0.5, seed=7)
    seq_a = [a.backoff(i) for i in range(6)]
    seq_b = [b.backoff(i) for i in range(6)]
    assert seq_a == seq_b                       # same seed, same schedule
    for i, v in enumerate(seq_a):
        base = min(0.5, 0.1 * 2 ** i)
        assert base <= v <= base * 1.1 + 1e-12  # jitter is additive, <=10%
    assert RetryPolicy(seed=8).backoff(0) != a.backoff(0)


def test_run_sleeps_exactly_the_seeded_schedule():
    clock = FakeClock(50.0)
    slept = []
    pol = _policy(clock, retries=3, timeout_s=1.0, backoff_s=0.1,
                  backoff_max_s=2.0, seed=3)
    pol._sleep = slept.append      # record instead of advancing
    calls = []

    def attempt(timeout_s):
        calls.append(timeout_s)
        if len(calls) < 3:
            raise ConnectionResetError("flaky")
        return "ok"

    assert pol.run(attempt, peer="h:9", op="pull") == "ok"
    assert calls == [1.0, 1.0, 1.0]            # per-attempt deadline set
    twin = RetryPolicy(backoff_s=0.1, backoff_max_s=2.0, seed=3)
    assert slept == [twin.backoff(0), twin.backoff(1)]


# ----------------------------------------------------------------------
# run(): retries, counters, final flight dump
# ----------------------------------------------------------------------

def test_run_retries_then_succeeds_counts_retries():
    telemetry.configure(enabled=True)
    telemetry.reset()
    clock = FakeClock(10.0)
    pol = _policy(clock, retries=2, timeout_s=0.5)
    seen = {"n": 0}

    def attempt(timeout_s):
        seen["n"] += 1
        if seen["n"] == 1:
            raise ConnectionRefusedError("first one fails")
        return 42

    assert pol.run(attempt, peer="h:1", op="pull") == 42
    c = _counters()
    assert c.get("rpc.retries") == 1
    assert c.get("rpc.retries.pull") == 1
    assert c.get("rpc.unreachable") == 1
    assert not c.get("rpc.failures")           # it recovered


def test_run_exhausted_raises_typed_and_dumps_flight(tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    telemetry.configure(enabled=True)
    telemetry.reset()
    clock = FakeClock(10.0)
    pol = _policy(clock, retries=2, timeout_s=0.5, seed=1)

    def attempt(timeout_s):
        raise socket.timeout("dead peer")

    with pytest.raises(RPCTimeout) as ei:
        pol.run(attempt, peer="h:7", op="push")
    assert ei.value.peer == "h:7" and ei.value.op == "push"
    assert ei.value.attempts == 3              # 1 + retries, all spent
    c = _counters()
    assert c.get("rpc.retries") == 2
    assert c.get("rpc.timeouts") == 3
    assert c.get("rpc.failures") == 1
    evs = [e for e in telemetry.events() if e["kind"] == "rpc.failed"]
    assert evs and evs[-1]["data"]["op"] == "push"
    assert evs[-1]["data"]["error"] == "RPCTimeout"
    # the final failure left a flight dump naming the op
    import json
    path = telemetry.last_flight_dump()
    assert path and str(tmp_path) in path
    with open(path) as f:
        assert json.load(f)["reason"] == "rpc_failure:push"


def test_total_deadline_beats_remaining_retry_budget():
    telemetry.configure(enabled=True)
    telemetry.reset()
    clock = FakeClock(100.0)
    # each failed attempt "takes" 1s of fake time; the total deadline
    # (2.5s) must cut the run short even though 9 retries remain
    pol = _policy(clock, retries=9, timeout_s=5.0, backoff_s=0.01,
                  deadline_s=2.5)
    calls = []

    def attempt(timeout_s):
        calls.append(timeout_s)
        clock.advance(1.0)
        raise ConnectionRefusedError("down")

    with pytest.raises(RPCTimeout) as ei:
        pol.run(attempt, peer="h:3", op="pull")
    assert "deadline" in str(ei.value)
    assert len(calls) < 10                    # budget NOT exhausted


def test_reconnect_runs_before_every_reattempt():
    clock = FakeClock(5.0)
    pol = _policy(clock, retries=2, timeout_s=1.0)
    order = []

    def attempt(timeout_s):
        order.append("attempt")
        if order.count("attempt") < 3:
            raise BrokenPipeError("poisoned framing")
        return "ok"

    def reconnect(timeout_s):
        order.append("reconnect")

    assert pol.run(attempt, reconnect=reconnect, peer="h", op="pull") \
        == "ok"
    # never before the FIRST attempt; always before a re-attempt
    assert order == ["attempt", "reconnect", "attempt", "reconnect",
                     "attempt"]


def test_failed_reconnect_consumes_the_attempt():
    telemetry.configure(enabled=True)
    telemetry.reset()
    clock = FakeClock(5.0)
    pol = _policy(clock, retries=1, timeout_s=1.0)
    attempts = []

    def attempt(timeout_s):
        attempts.append(1)
        raise ConnectionResetError("reset")

    def reconnect(timeout_s):
        raise ConnectionRefusedError("still down")

    with pytest.raises(PeerUnreachable):
        pol.run(attempt, reconnect=reconnect, peer="h:2", op="push")
    assert len(attempts) == 1   # the re-attempt died inside reconnect


def test_non_transport_errors_are_not_retried():
    clock = FakeClock(5.0)
    pol = _policy(clock, retries=5, timeout_s=1.0)
    calls = []

    def attempt(timeout_s):
        calls.append(1)
        raise ValueError("a server-side typed rejection, not transport")

    with pytest.raises(ValueError):
        pol.run(attempt, peer="h", op="join")
    assert len(calls) == 1


# ----------------------------------------------------------------------
# env knobs
# ----------------------------------------------------------------------

def test_from_env_kill_switch_single_attempt():
    pol = RetryPolicy.from_env(env={"MXTPU_RPC_RETRIES": "0"})
    assert pol.retries == 0
    slept = []
    pol._sleep = slept.append
    calls = []

    def attempt(timeout_s):
        calls.append(1)
        raise ConnectionRefusedError("down")

    with pytest.raises(PeerUnreachable):
        pol.run(attempt, peer="h", op="pull")
    assert len(calls) == 1 and slept == []    # exactly pre-19 one-shot


def test_from_env_defaults_and_zero_timeout_blocks_forever():
    pol = RetryPolicy.from_env(env={})
    assert pol.retries == 2
    assert pol.timeout_s == 5.0
    assert pol.deadline_s is None
    # 0 disables the per-attempt deadline (block forever, pre-19)
    nolimit = RetryPolicy.from_env(env={"MXTPU_RPC_TIMEOUT_S": "0"})
    assert nolimit.timeout_s is None
    # garbage values fall back instead of crashing the transport
    junk = RetryPolicy.from_env(env={"MXTPU_RPC_RETRIES": "lots"})
    assert junk.retries == 2


def test_from_env_overrides_win():
    pol = RetryPolicy.from_env(env={"MXTPU_RPC_RETRIES": "9"},
                               retries=1, deadline_s=3.0)
    assert pol.retries == 1 and pol.deadline_s == 3.0


def test_once_is_single_attempt_with_same_deadlines():
    pol = RetryPolicy(retries=5, timeout_s=2.0, deadline_s=9.0)
    one = pol.once()
    assert one.retries == 0
    assert one.timeout_s == 2.0 and one.deadline_s == 9.0
    # "block forever" (timeout 0/None) survives the copy
    assert RetryPolicy(retries=3, timeout_s=0).once().timeout_s is None


# ----------------------------------------------------------------------
# PSClient integration: typed connect failure, heartbeat swallow
# ----------------------------------------------------------------------

def _dead_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()                   # nothing listens here anymore
    return port


def _offline_client(policy):
    """A PSClient wired to a closed socket + dead port: every attempt
    and every reconnect fails fast and typed, no server needed."""
    from mxnet_tpu.kvstore.ps_server import PSClient
    client = PSClient.__new__(PSClient)       # skip the connect loop
    client._policy = policy
    client._addr = ("127.0.0.1", _dead_port())
    client._lock = threading.Lock()
    client._hb_stop = None
    sock = socket.socket()
    sock.close()                              # every op fails typed
    client._sock = sock
    return client


def test_mutating_ops_single_attempt_reads_keep_the_budget(tmp_path,
                                                           monkeypatch):
    """push applies ``w += grad`` server-side: a reply lost AFTER the
    server processed it would make a blind resend apply the gradient
    twice — so mutating ops must never burn the retry budget, while
    read-only pull keeps it (ISSUE 19 review)."""
    import numpy as np
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    telemetry.configure(enabled=True)
    telemetry.reset()
    client = _offline_client(RetryPolicy(retries=3, timeout_s=0.2,
                                         sleep=lambda s: None))
    for call in (lambda: client.push("w", np.zeros(1, np.float32)),
                 lambda: client.init("w", np.zeros(1, np.float32)),
                 lambda: client.send_command(0, "lr:0.1")):
        with pytest.raises(RPCError) as ei:
            call()
        assert ei.value.attempts == 1         # exactly one shot
    assert not _counters().get("rpc.retries")  # no resend ever happened
    with pytest.raises(RPCError) as ei:
        client.pull("w")
    assert ei.value.attempts == 4             # 1 + retries, all spent
    assert _counters().get("rpc.retries") == 3
    client.close()


def test_closed_client_fails_fast_and_never_reconnects():
    """close() is lock-free so it can interrupt a blocked exchange; a
    retry racing it must fail typed, not reconnect a fresh socket on a
    client the owner believes is closed (ISSUE 19 review)."""
    client = _offline_client(RetryPolicy(retries=2, timeout_s=0.2,
                                         sleep=lambda s: None))
    client.close()
    with pytest.raises(PeerUnreachable):
        client.pull("w")
    with pytest.raises(PeerUnreachable):      # the reconnect seam itself
        client._connect(0.1)


def test_psclient_connect_failure_is_typed_with_evidence(tmp_path,
                                                         monkeypatch):
    from mxnet_tpu.kvstore.ps_server import PSClient
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    telemetry.configure(enabled=True)
    telemetry.reset()
    port = _dead_port()
    pol = RetryPolicy(retries=0, timeout_s=0.5)
    with pytest.raises(PeerUnreachable) as ei:
        PSClient("127.0.0.1", port, retries=1, policy=pol)
    assert ei.value.op == "connect"
    assert ei.value.peer == f"127.0.0.1:{port}"
    assert _counters().get("rpc.failures") == 1
    assert telemetry.last_flight_dump()       # connect death left a dump


def test_beat_once_swallows_transport_errors_and_counts():
    """A missed beat is the heartbeat DETECTOR's job to judge: the
    beating worker must never crash on a transport error (ISSUE 19)."""
    from mxnet_tpu.kvstore.ps_server import PSClient
    telemetry.configure(enabled=True)
    telemetry.reset()
    client = PSClient.__new__(PSClient)       # skip the connect loop
    client._policy = RetryPolicy(retries=0, timeout_s=0.2)
    client._addr = ("127.0.0.1", _dead_port())
    client._lock = threading.Lock()
    client._hb_stop = None
    sock = socket.socket()
    sock.close()                              # every op fails typed
    client._sock = sock
    assert client.beat_once(0) is False
    assert _counters().get("rpc.heartbeat.dropped") == 1
    client.close()


def test_rpc_error_hierarchy():
    assert issubclass(RPCTimeout, RPCError)
    assert issubclass(PeerUnreachable, RPCError)
    assert issubclass(RPCError, ConnectionError)
