"""Flash attention (mxnet_tpu.ops.flash_attention) vs naive reference.

The kernel must match softmax(QK^T/sqrt(d))V exactly (same algorithm,
different memory schedule) in both values and gradients — the reference's
check_consistency idea (SURVEY.md §4.2) applied CPU-scan vs naive-XLA.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops import flash_attention


def _naive(q, k, v, causal=False, scale=None):
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d) if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        lq, lk = s.shape[-2:]
        mask = np.tril(np.ones((lq, lk), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_naive(causal):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 3, 64, 16), jnp.float32)
    k = jnp.asarray(rng.randn(2, 3, 64, 16), jnp.float32)
    v = jnp.asarray(rng.randn(2, 3, 64, 16), jnp.float32)
    out = flash_attention(q, k, v, causal=causal)
    ref = _naive(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_cross_attention_shapes():
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 48, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 96, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 96, 8), jnp.float32)
    out = flash_attention(q, k, v)
    ref = _naive(q, k, v)
    assert out.shape == (1, 2, 48, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_naive(causal):
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 2, 32, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 32, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 32, 8), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(_naive(q, k, v, causal=causal) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ndarray_tape_integration():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    rng = np.random.RandomState(3)
    q = mx.nd.array(rng.randn(1, 2, 16, 8).astype("float32"))
    k = mx.nd.array(rng.randn(1, 2, 16, 8).astype("float32"))
    v = mx.nd.array(rng.randn(1, 2, 16, 8).astype("float32"))
    q.attach_grad()
    with autograd.record():
        out = flash_attention(q, k, v)
        loss = (out * out).sum()
    loss.backward()
    ref = jax.grad(lambda q_, k_, v_: jnp.sum(
        _naive(q_, k_, v_) ** 2))(q.data, k.data, v.data)
    np.testing.assert_allclose(np.asarray(q.grad.data), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_mha_use_flash_matches_einsum_path():
    from mxnet_tpu.gluon.model_zoo.nlp.attention import MultiHeadAttention
    import mxnet_tpu as mx
    rng = np.random.RandomState(4)
    x = mx.nd.array(rng.randn(2, 12, 16).astype("float32"))
    cell = MultiHeadAttention(units=16, num_heads=4, use_flash=True)
    cell.initialize()
    out_flash = cell(x)                          # eval mode -> flash path
    cell._use_flash = False
    out_ref = cell(x)
    np.testing.assert_allclose(out_flash.asnumpy(), out_ref.asnumpy(),
                               rtol=2e-5, atol=2e-5)


def test_pallas_kernel_structure_compiles_in_interpret_mode():
    """Exercise the Pallas kernel itself (interpret=True on CPU)."""
    try:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    except (ImportError, NotImplementedError) as exc:
        pytest.skip(f"pallas-tpu unavailable in CPU test env: {exc}")
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 128, 128), jnp.float32)
    k = jnp.asarray(rng.randn(2, 128, 128), jnp.float32)
    v = jnp.asarray(rng.randn(2, 128, 128), jnp.float32)
    import mxnet_tpu.ops.flash_attention as mod
    orig = mod._pallas_forward

    import functools
    from unittest import mock

    def interp_forward(q, k, v, causal, sm_scale, bq, bk):
        with jax.disable_jit(False):
            return _interp(q, k, v, causal, sm_scale, bq, bk)

    def _interp(q, k, v, causal, sm_scale, bq, bk):
        # re-run the real builder but with interpret=True
        with mock.patch.object(pl, "pallas_call",
                               functools.partial(pl.pallas_call,
                                                 interpret=True)):
            return orig(q, k, v, causal, sm_scale, bq, bk)

    for causal in (False, True):
        out, lse = interp_forward(q, k, v, causal, 1.0 / np.sqrt(128.0),
                                  128, 128)
        ref, ref_lse = mod._scan_forward(q, k, v, causal,
                                         1.0 / np.sqrt(128.0), 128)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                                   rtol=2e-5, atol=2e-5)
