"""NLP model zoo tests (BERT / Transformer / LM / beam search).

Reference test strategy: tiny-shape forward+grad checks per model family
(SURVEY.md §4); models are exercised hybridized (XLA) and eager.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import nlp


def test_multihead_attention_shapes():
    cell = nlp.MultiHeadAttention(units=16, num_heads=4)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 5, 16))
    out = cell(x)
    assert out.shape == (2, 5, 16)
    # causal must not attend to the future: perturb the last position and
    # check position 0 output is unchanged
    y = cell(x, x, x, None, True)
    x2 = np.array(x.asnumpy())
    x2[:, -1, :] += 100.0
    y2 = cell(mx.nd.array(x2), mx.nd.array(x2), mx.nd.array(x2), None, True)
    np.testing.assert_allclose(y.asnumpy()[:, 0], y2.asnumpy()[:, 0],
                               rtol=2e-4, atol=2e-4)


# slow-marked (ISSUE 18 tier-1 headroom): BERT coverage stays via
# test_bert_hybridize + test_transformer_forward_and_causality
@pytest.mark.slow
@pytest.mark.slow   # heaviest BERT build; forward parity stays tier-1
# via test_bert_hybridize and backward via test_gluon's encoder-remat
# test (ISSUE 20 tier-1 headroom)
def test_bert_tiny_forward_and_grad():
    model = nlp.get_bert_model(num_layers=2, units=32, hidden_size=64,
                               num_heads=4, vocab_size=100, max_length=32)
    model.initialize()
    ids = mx.nd.array(np.random.randint(0, 100, (2, 9)), dtype="int32")
    types = mx.nd.zeros((2, 9), dtype="int32")
    vlen = mx.nd.array([9, 5])
    pos = mx.nd.array(np.array([[1, 2], [3, 4]]), dtype="int32")
    seq, pooled, mlm, nsp = model(ids, types, vlen, pos)
    assert seq.shape == (2, 9, 32)
    assert pooled.shape == (2, 32)
    assert mlm.shape == (2, 2, 100)
    assert nsp.shape == (2, 2)
    # padding positions must not influence the first token of row 1
    ids2 = np.array(ids.asnumpy())
    ids2[1, 7:] = 1  # change padded tokens (valid_length=5)
    seq2, _, _, _ = model(mx.nd.array(ids2, dtype="int32"), types, vlen, pos)
    np.testing.assert_allclose(seq.asnumpy()[1, 0], seq2.asnumpy()[1, 0],
                               rtol=1e-4, atol=1e-4)
    # gradient flows
    with autograd.record():
        _, _, mlm, _ = model(ids, types, vlen, pos)
        loss = gluon.loss.SoftmaxCrossEntropyLoss()(
            mlm.reshape((-1, 100)), mx.nd.zeros((4,)))
    loss.backward()
    w = model.word_embed.weight.grad()
    assert float(mx.nd.norm(w).asnumpy()) > 0


def test_bert_hybridize():
    model = nlp.get_bert_model(num_layers=1, units=16, hidden_size=32,
                               num_heads=2, vocab_size=50, max_length=16,
                               use_decoder=False, use_classifier=False)
    model.initialize()
    model.hybridize()
    ids = mx.nd.array(np.random.randint(0, 50, (2, 7)), dtype="int32")
    types = mx.nd.zeros((2, 7), dtype="int32")
    seq, pooled = model(ids, types)
    assert seq.shape == (2, 7, 16)
    assert pooled.shape == (2, 16)
    # eager vs hybrid agree
    seq_h = seq.asnumpy()
    model.hybridize(False)
    seq_e, _ = model(ids, types)
    np.testing.assert_allclose(seq_h, seq_e.asnumpy(), rtol=1e-5, atol=1e-5)


def test_transformer_forward_and_causality():
    model = nlp.TransformerModel(src_vocab_size=40, tgt_vocab_size=40,
                                 num_layers=2, units=16, hidden_size=32,
                                 num_heads=2, max_length=32, dropout=0.0)
    model.initialize()
    src = mx.nd.array(np.random.randint(0, 40, (2, 6)), dtype="int32")
    tgt = mx.nd.array(np.random.randint(0, 40, (2, 5)), dtype="int32")
    out = model(src, tgt, mx.nd.array([6, 4]))
    assert out.shape == (2, 5, 40)
    # decoder causality: changing tgt[t=4] must not change logits at t<4
    tgt2 = np.array(tgt.asnumpy())
    tgt2[:, 4] = (tgt2[:, 4] + 1) % 40
    out2 = model(src, mx.nd.array(tgt2, dtype="int32"), mx.nd.array([6, 4]))
    np.testing.assert_allclose(out.asnumpy()[:, :4], out2.asnumpy()[:, :4],
                               rtol=1e-4, atol=1e-4)


def test_language_model_forward():
    model = nlp.standard_lstm_lm_200(vocab_size=30)
    model.initialize()
    x = mx.nd.array(np.random.randint(0, 30, (7, 2)), dtype="int32")
    logits, state = model(x)
    assert logits.shape == (7, 2, 30)
    model2 = nlp.awd_lstm_lm_600(vocab_size=30)
    model2.initialize()
    logits2, _ = model2(x)
    assert logits2.shape == (7, 2, 30)


def test_beam_search_prefers_high_prob_path():
    # toy decoder: always emits log-probs favoring token 3, EOS=0 after it
    vocab = 5

    def decoder(step_input, states):
        step = int(states["step"].asnumpy()[0]) if hasattr(
            states["step"], "asnumpy") else int(states["step"][0])
        import jax.numpy as jnp
        n = step_input.shape[0]
        lp = np.full((n, vocab), -10.0, dtype=np.float32)
        if step == 0:
            lp[:, 3] = -0.1
        else:
            lp[:, 0] = -0.1  # EOS
        states = {"step": mx.nd.array([step + 1])}
        return mx.nd.array(lp), states

    sampler = nlp.BeamSearchSampler(beam_size=2, decoder=decoder, eos_id=0,
                                    max_length=4)
    samples, scores, lengths = sampler(mx.nd.array([1, 1]),
                                       {"step": mx.nd.array([0])})
    s = samples.asnumpy()
    assert s.shape[0] == 2 and s.shape[1] == 2
    # best beam: start token, then 3, then EOS
    assert s[0, 0, 1] == 3
    assert 0 in s[0, 0, 2:]


def test_sequence_sampler_determinism_via_key_data():
    """SequenceSampler draws from the global mx.random stream:
    snapshotting the key with random.get_key_data and restoring it with
    set_key_data (the PR 4 checkpoint API) replays the exact sample —
    and without the restore the stream moves on."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ndarray import random as _rnd

    vocab = 12

    @jax.jit
    def step(tok, states):
        logits = jnp.tile(jnp.linspace(0.0, 1.0, vocab)[None, :],
                          (tok.shape[0], 1))
        return jax.nn.log_softmax(logits, axis=-1), states

    sampler = nlp.SequenceSampler(beam_size=3, decoder=step, eos_id=0,
                                  max_length=6, temperature=1.0, top_k=4)
    snap = np.asarray(_rnd.get_key_data()).copy()
    s1, sc1, l1 = sampler(mx.nd.array([1, 2]), {})
    _rnd.set_key_data(snap)
    s2, sc2, l2 = sampler(mx.nd.array([1, 2]), {})
    np.testing.assert_array_equal(s1.asnumpy(), s2.asnumpy())
    np.testing.assert_array_equal(l1.asnumpy(), l2.asnumpy())
    # stream NOT restored -> (vanishingly likely) different draws
    s3, _, _ = sampler(mx.nd.array([1, 2]), {})
    assert not np.array_equal(s1.asnumpy(), s3.asnumpy())
    # top_k=4 with an ascending logit ramp: only the 4 best ids appear
    gen = s1.asnumpy()[..., 1:]
    assert set(np.unique(gen)).issubset(set(range(vocab - 4, vocab)))
