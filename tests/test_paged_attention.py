"""Paged decode attention (mxnet_tpu.ops.paged_attention), ISSUE 17.

The gate that matters on CPU: the XLA fallback is BITWISE the engine's
original inline formulation (dense gather through the block table +
``llama._cache_attention``) — so ``MXTPU_PAGED_ATTN`` is a bitwise-inert
routing knob anywhere the Pallas body doesn't engage.  The Pallas body
itself compiles only on TPU backends; here we assert its ROUTING
(``_use_pallas`` geometry gate) and skip execution off-TPU, the
flash_attention discipline.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops import paged_decode_attention
from mxnet_tpu.ops.paged_attention import _fallback, _use_pallas

_ON_TPU = jax.default_backend() == "tpu"


def _geometry(rng, B=3, h=4, kvh=2, d=8, num_blocks=12, bs=4, nbl=3):
    """Random pools + per-sequence block tables with DISTINCT physical
    blocks and ragged positions (some sequences mid-block, write-ahead
    garbage past pos)."""
    q = jnp.asarray(rng.randn(B, h, d), jnp.float32)
    k_pool = jnp.asarray(rng.randn(num_blocks, bs, kvh, d), jnp.float32)
    v_pool = jnp.asarray(rng.randn(num_blocks, bs, kvh, d), jnp.float32)
    # non-trivial tables: out-of-order physical blocks, 0 as null pad
    tables = np.zeros((B, nbl), np.int32)
    perm = rng.permutation(np.arange(1, num_blocks))
    tables[0] = perm[:nbl]                      # full context
    tables[1, :2] = perm[nbl:nbl + 2]           # 2 blocks + null pad
    tables[2, :1] = perm[nbl + 2:nbl + 3]       # mid-first-block
    tables = jnp.asarray(tables)
    pos = jnp.asarray([nbl * bs - 1, bs + 1, 1], jnp.int32)
    scale = 1.0 / np.sqrt(d)
    return q, k_pool, v_pool, tables, pos, scale


def _inline_reference(q, k_pool, v_pool, tables, pos, scale):
    """The engine's pre-ISSUE-17 decode attention, hand-inlined (the
    exact expression the fallback replaced)."""
    from mxnet_tpu.gluon.model_zoo.nlp.llama import _cache_attention
    B = q.shape[0]
    nbl = tables.shape[1]
    bs, kvh, d = k_pool.shape[1:]
    L = nbl * bs
    ck = k_pool[tables].reshape(B, L, kvh, d).transpose(0, 2, 1, 3)
    cv = v_pool[tables].reshape(B, L, kvh, d).transpose(0, 2, 1, 3)
    valid = jnp.arange(L)[None, :] <= pos[:, None]
    return _cache_attention(q, ck, cv, valid, scale)


def test_fallback_bitwise_matches_inline_gather():
    rng = np.random.RandomState(0)
    args = _geometry(rng)
    out = _fallback(*args)
    ref = _inline_reference(*args)
    assert out.shape == ref.shape == (3, 4 * 8)
    # BITWISE, not allclose: same ops in the same order
    assert np.array_equal(np.asarray(out), np.asarray(ref))


def test_public_entry_routes_to_fallback_off_tpu():
    if _ON_TPU:
        pytest.skip("TPU backend: the Pallas body engages")
    rng = np.random.RandomState(1)
    args = _geometry(rng)
    out = paged_decode_attention(*args)
    assert np.array_equal(np.asarray(out),
                          np.asarray(_inline_reference(*args)))


def test_fallback_masks_write_ahead_garbage():
    """Positions past ``pos`` (verify write-ahead, table padding) must
    contribute exactly nothing: poisoning them cannot move the output."""
    rng = np.random.RandomState(2)
    q, k_pool, v_pool, tables, pos, scale = _geometry(rng)
    out = _fallback(q, k_pool, v_pool, tables, pos, scale)
    # poison every pool position a sequence is NOT allowed to see; the
    # null block 0 is shared as padding, so poison a row 1's pad target
    kp = np.asarray(k_pool).copy()
    vp = np.asarray(v_pool).copy()
    poison_blk = int(np.asarray(tables)[1, 2])   # the null pad block
    kp[poison_blk] = 1e6
    vp[poison_blk] = -1e6
    # row 2 sees only positions 0..1 of its first block: poison the rest
    blk2 = int(np.asarray(tables)[2, 0])
    kp[blk2, 2:] = 1e6
    vp[blk2, 2:] = -1e6
    out2 = _fallback(q, jnp.asarray(kp), jnp.asarray(vp), tables, pos,
                     scale)
    # row 0 attends everything it owns — untouched rows stay bitwise;
    # rows 1 and 2 must not see the poison
    assert np.array_equal(np.asarray(out2[1]), np.asarray(out[1]))
    assert np.array_equal(np.asarray(out2[2]), np.asarray(out[2]))


def test_use_pallas_geometry_gate():
    if _ON_TPU:
        # on TPU the gate is geometric only
        assert _use_pallas(block_size=8, kv_heads=2, head_dim=64)
    else:
        assert not _use_pallas(block_size=8, kv_heads=2, head_dim=64)
    # geometries Mosaic can't tile decline everywhere
    assert not _use_pallas(block_size=8, kv_heads=2, head_dim=48)
    assert not _use_pallas(block_size=6, kv_heads=2, head_dim=64)


def test_pallas_body_matches_fallback_on_tpu():
    if not _ON_TPU:
        pytest.skip("Pallas paged kernel compiles on TPU only")
    from mxnet_tpu.ops.paged_attention import _pallas_paged
    rng = np.random.RandomState(3)
    # a Mosaic-tileable geometry: d=64, bs=8
    q = jnp.asarray(rng.randn(2, 4, 64), jnp.float32)
    k_pool = jnp.asarray(rng.randn(8, 8, 2, 64), jnp.float32)
    v_pool = jnp.asarray(rng.randn(8, 8, 2, 64), jnp.float32)
    tables = jnp.asarray([[3, 1, 0], [5, 0, 0]], jnp.int32)
    pos = jnp.asarray([13, 4], jnp.int32)
    out = _pallas_paged(q, k_pool, v_pool, tables, pos, 0.125)
    ref = _fallback(q, k_pool, v_pool, tables, pos, 0.125)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
